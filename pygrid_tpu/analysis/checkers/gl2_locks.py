"""GL2 — thread/lock discipline.

The stack runs real threads: each serving engine owns a device-loop
thread, the cycle manager aggregates on the background task pool, the
telemetry bus is hit from every thread at once, and WS handlers run on
a dedicated executor. The classic hazards:

- **GL201** lock-order cycles: function F acquires lock B while holding
  lock A, function G acquires A while holding B — a deadlock waiting
  for the right interleaving. Locks are identified per ``(file, class,
  attr)``; the acquisition graph is global across the run.
- **GL202** unlocked mutation of lock-protected state: a class that
  constructs a ``threading.Lock``/``RLock``/``Condition`` in
  ``__init__`` and touches ``self._x`` under ``with self._lock`` in one
  method must not mutate the same ``self._x`` lock-free in another.
  The "touched under the lock somewhere" filter is the precision knob:
  attributes a class never guards are treated as thread-confined by
  design (suppress with a justification comment where a single-writer
  thread owns them). Two caller-holds-the-lock conventions this repo
  already uses are recognized: methods named ``*_locked`` and methods
  whose docstring opens with ``"Under the lock"`` are exempt — their
  contract is that the caller acquired the lock.
- **GL203** aliased-lock self-deadlock: ``with self._work:`` nested
  inside ``with self._lock:`` when ``self._work =
  threading.Condition(self._lock)`` — the same non-reentrant lock
  acquired twice on one thread.
"""

from __future__ import annotations

import ast
from typing import Iterable

from pygrid_tpu.analysis.core import Checker, Finding, ModuleContext
from pygrid_tpu.analysis.checkers.gl1_trace import _dotted

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
#: RLock/Semaphore may be re-acquired by design — GL203 exempts them
_REENTRANT_CTORS = {"RLock", "Semaphore", "BoundedSemaphore"}

#: method names that mutate common containers in place
_MUTATING_METHODS = {
    "append", "extend", "insert", "remove", "pop", "popleft", "appendleft",
    "clear", "add", "discard", "update", "setdefault", "put", "put_nowait",
}


def _lock_ctor_name(value: ast.AST) -> str | None:
    """``threading.Lock()`` / ``Condition(x)`` → the ctor name."""
    if isinstance(value, ast.Call):
        dotted = _dotted(value.func)
        if dotted:
            short = dotted.split(".")[-1]
            if short in _LOCK_CTORS:
                return short
    return None


class _ClassInfo:
    def __init__(self, mod: ModuleContext, node: ast.ClassDef) -> None:
        self.mod = mod
        self.node = node
        self.name = node.name
        self.locks: dict[str, str] = {}  # attr -> ctor name
        self.aliases: dict[str, str] = {}  # attr -> attr it wraps
        # attr -> mutation sites [(node, holding_locks)]
        self.mutations: dict[str, list[tuple[ast.AST, frozenset[str]]]] = {}
        # attr -> read sites under a lock
        self.guarded_touch: set[str] = set()

    def lock_id(self, attr: str) -> tuple[str, str, str]:
        return (self.mod.rel_path, self.name, attr)


def _self_attr(node: ast.AST) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id in ("self", "cls")
    ):
        return node.attr
    return None


class _MethodScan(ast.NodeVisitor):
    """One method body: mutations/touches of self attrs vs held locks,
    plus lock-acquisition nesting edges."""

    def __init__(self, info: _ClassInfo) -> None:
        self.info = info
        self.held: list[str] = []  # stack of held lock attrs (canonical)
        self.edges: list[tuple[str, str, ast.AST]] = []
        self.self_deadlocks: list[tuple[ast.AST, str, str]] = []
        #: ``lock = self._lock`` aliases seen so far — ``with lock:``
        #: then resolves to the canonical attr (scan is source-ordered,
        #: so the assignment precedes the with that uses it)
        self.local_locks: dict[str, str] = {}

    def _canonical(self, attr: str) -> str:
        return self.info.aliases.get(attr, attr)

    def _with_lock_attr(self, expr: ast.AST) -> str | None:
        attr = _self_attr(expr)
        if attr is None and isinstance(expr, ast.Name):
            attr = self.local_locks.get(expr.id)
        return attr

    def _discard_aliases(self, target: ast.AST | None) -> None:
        """ANY binding construct rebinding an aliased name — tuple
        unpack, for target, with-as — kills the alias: stale aliases
        guard regions with a lock that is not held."""
        if target is None:
            return
        for node in ast.walk(target):
            if isinstance(node, ast.Name):
                self.local_locks.pop(node.id, None)

    def visit_With(self, node: ast.With) -> None:
        acquired: list[str] = []
        for item in node.items:
            attr = self._with_lock_attr(item.context_expr)
            self._discard_aliases(item.optional_vars)
            if attr is not None and attr in self.info.locks:
                canon = self._canonical(attr)
                for held in self.held:
                    self.edges.append((held, canon, item.context_expr))
                    if held == canon and (
                        self.info.locks.get(canon) not in _REENTRANT_CTORS
                    ):
                        self.self_deadlocks.append(
                            (item.context_expr, attr, held)
                        )
                self.held.append(canon)
                acquired.append(canon)
        self.generic_visit(node)
        for _ in acquired:
            self.held.pop()

    visit_AsyncWith = visit_With

    def _record_mutation(self, attr: str, node: ast.AST) -> None:
        if attr in self.info.locks:
            return
        self.info.mutations.setdefault(attr, []).append(
            (node, frozenset(self.held))
        )
        if self.held:
            self.info.guarded_touch.add(attr)

    def visit_Assign(self, node: ast.Assign) -> None:
        # local lock alias: ``lock = self._lock``; rebinding the name
        # by ANY other construct (plain assign, tuple unpack) DISCARDS
        # the alias (a stale alias would mark unguarded regions as
        # guarded)
        if len(node.targets) == 1 and isinstance(
            node.targets[0], ast.Name
        ):
            value_attr = _self_attr(node.value)
            if value_attr is not None and value_attr in self.info.locks:
                self.local_locks[node.targets[0].id] = value_attr
            else:
                self.local_locks.pop(node.targets[0].id, None)
        else:
            for target in node.targets:
                self._discard_aliases(target)
        for target in node.targets:
            for el in (
                target.elts if isinstance(target, ast.Tuple) else [target]
            ):
                attr = _self_attr(el)
                if attr is not None:
                    self._record_mutation(attr, node)
                # self._x[...] = ...
                if isinstance(el, ast.Subscript):
                    attr = _self_attr(el.value)
                    if attr is not None:
                        self._record_mutation(attr, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        attr = _self_attr(node.target)
        if attr is None and isinstance(node.target, ast.Subscript):
            attr = _self_attr(node.target.value)
        if attr is not None:
            self._record_mutation(attr, node)
        if isinstance(node.target, ast.Name):
            self._discard_aliases(node.target)
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        self._discard_aliases(node.target)
        self.generic_visit(node)

    visit_AsyncFor = visit_For

    def visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            attr = _self_attr(target)
            if attr is None and isinstance(target, ast.Subscript):
                attr = _self_attr(target.value)
            if attr is not None:
                self._record_mutation(attr, node)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # self._x.append(...) and friends
        if isinstance(node.func, ast.Attribute):
            attr = _self_attr(node.func.value)
            if attr is not None and node.func.attr in _MUTATING_METHODS:
                self._record_mutation(attr, node)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        # reads under a lock mark the attr as lock-protected
        attr = _self_attr(node)
        if (
            attr is not None
            and attr not in self.info.locks
            and self.held
            and isinstance(node.ctx, ast.Load)
        ):
            self.info.guarded_touch.add(attr)
        self.generic_visit(node)


class LockDisciplineChecker(Checker):
    name = "GL2"
    description = "lock ordering + unlocked mutation of shared state"
    codes = {
        "GL201": "lock-acquisition-order cycle (potential deadlock)",
        "GL202": "lock-protected self._ state mutated outside the lock",
        "GL203": "non-reentrant lock re-acquired while held (self-deadlock)",
    }

    def __init__(self) -> None:
        # global acquisition graph: lock_id -> {lock_id: witness finding site}
        self._edges: dict[tuple, dict[tuple, tuple[ModuleContext, ast.AST]]] = {}

    def check_module(self, mod: ModuleContext) -> Iterable[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            info = _ClassInfo(mod, node)
            # pass 1: find lock attrs + aliases from __init__ (and class
            # body), e.g. self._work = threading.Condition(self._lock)
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    attr = _self_attr(sub.targets[0])
                    if attr is None:
                        continue
                    ctor = _lock_ctor_name(sub.value)
                    if ctor is not None:
                        info.locks[attr] = ctor
                        if (
                            ctor == "Condition"
                            and isinstance(sub.value, ast.Call)
                            and sub.value.args
                        ):
                            wrapped = _self_attr(sub.value.args[0])
                            if wrapped is not None:
                                info.aliases[attr] = wrapped
            if not info.locks:
                continue
            # Condition aliased over a Lock: both names are one lock; the
            # alias target inherits the wrapped ctor's reentrancy
            for alias, wrapped in info.aliases.items():
                if wrapped in info.locks:
                    info.locks[alias] = info.locks[wrapped]
            # pass 2: scan every method except __init__ (construction is
            # single-threaded by definition)
            for item in node.body:
                if not isinstance(
                    item, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if item.name == "__init__":
                    continue
                caller_holds_lock = item.name.endswith("_locked") or (
                    (ast.get_docstring(item) or "")
                    .lstrip()
                    .lower()
                    .startswith("under the lock")
                )
                scan = _MethodScan(info)
                if caller_holds_lock:
                    # the method's contract: callers acquired the lock —
                    # treat the whole body as guarded. The sentinel never
                    # matches a real lock attr, so it cannot fabricate
                    # GL201 ordering edges or GL203 re-acquisitions.
                    scan.held.append("<caller-held>")
                for stmt in item.body:
                    scan.visit(stmt)
                for held, acquired, site in scan.edges:
                    if held != acquired and held != "<caller-held>":
                        self._edges.setdefault(
                            info.lock_id(held), {}
                        ).setdefault(info.lock_id(acquired), (mod, site))
                for site, attr, _held in scan.self_deadlocks:
                    canon = info.aliases.get(attr, attr)
                    alias_note = (
                        f" ('{attr}' wraps '{canon}')"
                        if attr != canon
                        else ""
                    )
                    findings.append(
                        mod.finding(
                            "GL203",
                            site,
                            f"'{info.name}.{item.name}' re-acquires "
                            f"non-reentrant lock 'self.{canon}' it already "
                            f"holds{alias_note} — self-deadlock",
                        )
                    )
            # pass 3: unlocked mutations of attrs the class guards
            for attr, sites in info.mutations.items():
                if attr not in info.guarded_touch:
                    continue  # never guarded → treated as thread-confined
                for site, held in sites:
                    if not held:
                        findings.append(
                            mod.finding(
                                "GL202",
                                site,
                                f"'{info.name}' mutates lock-protected "
                                f"'self.{attr}' outside any 'with "
                                "self.<lock>' block",
                            )
                        )
        return findings

    def finalize(self, run) -> Iterable[Finding]:
        # cycle detection over the global acquisition graph
        findings: list[Finding] = []
        color: dict[tuple, int] = {}
        stack: list[tuple] = []
        reported: set[frozenset] = set()

        def _dfs(lock: tuple) -> None:
            color[lock] = 1
            stack.append(lock)
            for nxt, (mod, site) in self._edges.get(lock, {}).items():
                if color.get(nxt, 0) == 1:
                    cycle = stack[stack.index(nxt):] + [nxt]
                    key = frozenset(cycle)
                    if key not in reported:
                        reported.add(key)
                        pretty = " -> ".join(
                            f"{c[1]}.{c[2]}" for c in cycle
                        )
                        findings.append(
                            mod.finding(
                                "GL201",
                                site,
                                "lock-acquisition-order cycle: "
                                f"{pretty} (deadlock under contention)",
                            )
                        )
                elif color.get(nxt, 0) == 0:
                    _dfs(nxt)
            stack.pop()
            color[lock] = 2

        for lock in list(self._edges):
            if color.get(lock, 0) == 0:
                _dfs(lock)
        return findings
