"""GL1 — JAX trace-safety.

The failure mode: host side-effects inside code that runs under
``jax.jit``/``pjit`` fire once per TRACE, not once per call — telemetry
counters silently stop counting, locks are acquired at trace time and
never again, ``time.perf_counter()`` measures compilation instead of
execution, and ``.item()``/``int()`` forces a device sync (or a
ConcretizationError) in the middle of a compiled program. The serving
engine's no-recompile contract (``serving/programs.py``) also dies by a
thousand ``jax.jit(...)(x)`` cuts: a jit built per call retraces per
call.

**GL104 — donation-after-use**: a ``jit(..., donate_argnums=...)``
program may CONSUME its donated argument buffers (XLA reuses them for
the output); reading the donated name after the call raises a
``deleted buffer`` error at best and returns garbage at worst. The
paged serving engine's block-pool swap discipline (``toks, self._k,
self._v, self._pos = fn(self.params, self._k, ...)`` — donated names
reassigned in the SAME statement) is exactly what the rule guards:
per ``jit(...)`` site we record the donated positions, then flag any
later straight-line read of a name that was passed at a donated
position and not reassigned since. A reassignment (including by the
call's own tuple unpack) revives the name.

Detection is deliberately conservative: a function is *jitted* when it
is decorated with ``jit``/``pjit`` (bare, dotted, or via
``partial(jax.jit, ...)``) or its name/lambda is passed as the first
argument to a ``jit``/``pjit`` call anywhere in the module.
Reachability closes over module-level functions and same-class
``self.``/``cls.`` methods called from a jitted body, and — the
**two-pass whole-run extension** — over CROSS-MODULE calls: every
file's function index and import table feed a run-wide symbol table in
``finalize``, so a jitted body in ``serving/programs.py`` calling
``decode.step(...)`` pulls ``models/decode.py``'s ``step`` (and its
local closure, and any further imported hops) into the trace-safety
closure. Cross-module findings are attributed to the file that
contains the side effect; duplicates with that module's own local
closure are folded.
"""

from __future__ import annotations

import ast
from typing import Iterable

from pygrid_tpu.analysis.core import Checker, Finding, ModuleContext
from pygrid_tpu.analysis.graph import (
    FunctionIndex as _FunctionIndex,
    ImportIndex as _ImportIndexBase,
    dotted as _dotted,
    is_jit_callable as _is_jit_callable,
    module_dotted as _module_dotted,
    package_of as _package_of,
)

#: the per-module symbol tables live in analysis/graph.py now (the
#: whole-program core shares them with the GL2 concurrency checkers);
#: the aliases above keep this module's historical local names

#: ``module.attr`` calls that are host side-effects (GL101)
_SIDE_EFFECT_ATTRS = {
    ("telemetry", "record"), ("telemetry", "incr"), ("telemetry", "observe"),
    ("time", "time"), ("time", "sleep"), ("time", "perf_counter"),
    ("time", "monotonic"), ("time", "process_time"),
    ("os", "urandom"), ("random", "random"), ("random", "randint"),
}
#: bare-name calls that are host side-effects when invoked in a trace
_SIDE_EFFECT_NAMES = {"print", "record", "incr", "observe"}
#: logger-ish receivers: ``logger.info(...)`` etc.
_LOGGER_RECEIVERS = {"logger", "logging", "log"}
_LOGGER_METHODS = {
    "debug", "info", "warning", "error", "exception", "critical", "log",
}


class _TraceBodyScan(ast.NodeVisitor):
    """Walk one jitted body collecting side-effects and outgoing calls."""

    def __init__(self) -> None:
        self.effects: list[tuple[ast.AST, str, str]] = []  # node, code, msg
        self.calls: set[str] = set()

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Name):
            if fn.id in _SIDE_EFFECT_NAMES:
                self.effects.append(
                    (node, "GL101", f"host side-effect call '{fn.id}()'")
                )
            self.calls.add(fn.id)
        elif isinstance(fn, ast.Attribute):
            dotted = _dotted(fn)
            recv = dotted.split(".")[0] if dotted else ""
            if (recv, fn.attr) in _SIDE_EFFECT_ATTRS:
                self.effects.append(
                    (node, "GL101", f"host side-effect call '{recv}.{fn.attr}()'")
                )
            elif recv in _LOGGER_RECEIVERS and fn.attr in _LOGGER_METHODS:
                self.effects.append(
                    (node, "GL101", f"logging call '{recv}.{fn.attr}()'")
                )
            elif fn.attr == "acquire":
                self.effects.append(
                    (node, "GL101", f"lock acquisition '{dotted}()'")
                )
            elif fn.attr == "item" and not node.args:
                self.effects.append(
                    (
                        node,
                        "GL102",
                        "'.item()' forces a host sync inside a traced "
                        "function",
                    )
                )
            if dotted:
                self.calls.add(dotted)
                if dotted.startswith(("self.", "cls.")):
                    self.calls.add(dotted.split(".", 1)[1])
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        for item in node.items:
            dotted = _dotted(item.context_expr)
            if dotted and "lock" in dotted.rsplit(".", 1)[-1].lower():
                self.effects.append(
                    (
                        item.context_expr,
                        "GL101",
                        f"lock acquisition 'with {dotted}' inside a traced "
                        "function",
                    )
                )
        self.generic_visit(node)


def _literal_argnums(call: ast.Call, kwname: str) -> tuple[int, ...] | None:
    """Literal argnum positions of ``kwname`` on a ``jit/pjit(...)``
    call; None otherwise (dynamic positions are out of reach for a
    static rule — stay quiet, not wrong)."""
    if not isinstance(call, ast.Call) or not _is_jit_callable(call.func):
        return None
    for kw in call.keywords:
        if kw.arg != kwname:
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out: list[int] = []
            for elt in v.elts:
                if not (
                    isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)
                ):
                    return None
                out.append(elt.value)
            return tuple(out)
        return None
    return None


def _donate_positions(call: ast.Call) -> tuple[int, ...] | None:
    """Donated argnums if ``call`` is ``jit/pjit(..., donate_argnums=…)``
    with literal positions."""
    return _literal_argnums(call, "donate_argnums")


def _find_donating_jit(expr: ast.AST) -> tuple[int, ...] | None:
    """Donated positions of a ``jit(..., donate_argnums=…)`` call
    anywhere in ``expr`` — wrappers preserve the signature, so
    ``profiler.wrap(jax.jit(f, donate_argnums=(1,)), …)`` still donates
    position 1 of the wrapped callable."""
    for node in ast.walk(expr):
        pos = _donate_positions(node) if isinstance(node, ast.Call) else None
        if pos is not None:
            return pos
    return None


class _DonationChecker:
    """GL104 — donation-after-use, straight-line liveness per body.

    Pass 1 records every name assigned from an expression containing a
    donating jit; pass 2 walks each statement list in order: a call of
    a donor (or an immediately-invoked donating jit) KILLS the dotted
    names passed at donated positions, any read of a killed name is a
    finding, and any assignment (including the killing call's own tuple
    unpack — the engine's swap idiom) revives it. Kills never propagate
    out of nested bodies and any nested assignment revives, so the rule
    errs quiet, not wrong."""

    def __init__(self, mod: ModuleContext) -> None:
        self.mod = mod
        self.findings: list[Finding] = []
        self.donors: dict[str, tuple[int, ...]] = {}

    def run(self) -> list[Finding]:
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                name = _dotted(node.targets[0])
                if name is None:
                    continue
                pos = _find_donating_jit(node.value)
                if pos is not None:
                    self.donors[name] = pos
        self._body(self.mod.tree.body, {})
        for node in ast.walk(self.mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._body(node.body, {})
        return self.findings

    # ── statement-level helpers ──────────────────────────────────────

    @staticmethod
    def _assigned(stmt: ast.stmt) -> set[str]:
        """Dotted names (re)bound anywhere within ``stmt``."""
        out: set[str] = set()

        def _targets(t: ast.AST) -> None:
            if isinstance(t, (ast.Tuple, ast.List)):
                for elt in t.elts:
                    _targets(elt)
            else:
                name = _dotted(t)
                if name is not None:
                    out.add(name)

        for node in ast.walk(stmt):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    _targets(t)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                _targets(node.target)
            elif isinstance(node, ast.For):
                _targets(node.target)
            elif isinstance(node, ast.withitem) and node.optional_vars:
                _targets(node.optional_vars)
        return out

    @staticmethod
    def _walk_executed(stmt: ast.stmt):
        """``ast.walk`` minus Lambda / nested-def subtrees: code in a
        deferred body does NOT run at this statement's line, so a
        donating call inside a callback must not kill names here."""
        stack: list[ast.AST] = [stmt]
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef),
                ):
                    continue
                stack.append(child)

    def _kills(self, stmt: ast.stmt) -> list[tuple[str, int]]:
        """(dotted name, line) pairs donated by calls in ``stmt``."""
        out: list[tuple[str, int]] = []
        for node in self._walk_executed(stmt):
            if not isinstance(node, ast.Call):
                continue
            positions = None
            fname = _dotted(node.func)
            if fname is not None and fname in self.donors:
                positions = self.donors[fname]
            elif isinstance(node.func, ast.Call):
                # jit(f, donate_argnums=…)(args) invoked immediately
                positions = _donate_positions(node.func)
            if not positions:
                continue
            for i in positions:
                if 0 <= i < len(node.args):
                    name = _dotted(node.args[i])
                    if name is not None:
                        out.append((name, node.lineno))
        return out

    def _flag_reads(self, node: ast.AST, dead: dict[str, int]) -> None:
        seen: set[tuple[str, int]] = set()  # one finding per name+line
        for sub in ast.walk(node):
            if not isinstance(sub, (ast.Name, ast.Attribute)):
                continue
            if not isinstance(getattr(sub, "ctx", None), ast.Load):
                continue
            read = _dotted(sub)
            if read is None:
                continue
            for name, line in dead.items():
                if read != name and not read.startswith(name + "."):
                    continue
                key = (name, sub.lineno)
                if key not in seen:
                    seen.add(key)
                    self.findings.append(
                        self.mod.finding(
                            "GL104",
                            sub,
                            f"'{name}' was passed at a donated position "
                            f"(donate_argnums) on line {line} and read "
                            "before reassignment — XLA may have consumed "
                            "the buffer",
                        )
                    )
                break

    def _body(self, stmts: list[ast.stmt], dead: dict[str, int]) -> None:
        dead = dict(dead)
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # separate liveness domain (walked at run())
            nested = [
                sub
                for attr in ("body", "orelse", "finalbody")
                for sub in (getattr(stmt, attr, None) or [])
                if isinstance(sub, ast.stmt)
            ]
            if nested:
                # compound statement: only the header expressions are
                # straight-line here — bodies get their own walk
                for attr in ("test", "iter", "items"):
                    header = getattr(stmt, attr, None)
                    for part in header if isinstance(header, list) else (
                        [header] if header is not None else []
                    ):
                        self._flag_reads(part, dead)
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, attr, None)
                    if sub:
                        self._body(sub, dead)
                for handler in getattr(stmt, "handlers", []) or []:
                    self._body(handler.body, dead)
                # an assignment anywhere inside revives (a kill inside
                # stays inside): err quiet on branches
                for name in self._assigned(stmt):
                    dead.pop(name, None)
                continue
            assigned = self._assigned(stmt)
            self._flag_reads(stmt, dead)
            for name, line in self._kills(stmt):
                if name not in assigned:
                    dead[name] = line
            for name in assigned:
                dead.pop(name, None)


#: receivers whose subscripts / ``.get()`` reads carry PER-REQUEST data
#: (wire frames, JSON bodies, HTTP requests) — the taint sources GL105
#: follows into jit constructions
_REQUEST_NAMES = {
    "msg", "message", "payload", "request", "req", "body", "data",
    "query",
}
#: ``request.<attr>`` reads that ARE the request payload
_REQUEST_ATTRS = {"json", "query", "match_info", "rel_url", "post"}


class _ScalarTaintChecker:
    """GL105 — python-scalar-into-traced-signature.

    The ``n_new`` pathology PR 3 fixed: a host int read from a request
    (``int(data["n_new"])``) baked into a jitted program's STATIC
    surface — a lambda default / closure (``jax.jit(lambda p, x,
    n=n_new: ...)``) or a ``static_argnums`` position — compiles one
    XLA program per distinct client value. Light per-scope dataflow:
    names assigned from request/JSON reads (subscripts or ``.get()`` of
    request-ish receivers, ``request.json``, ``json.loads``, arithmetic
    or ``int()``/``float()`` over those) are tainted; a finding fires
    when a tainted name

    1. appears anywhere inside a ``jit(...)``/``pjit(...)``
       CONSTRUCTION expression (lambda default, ``partial`` binding —
       the closure-bake idiom),
    2. is a free variable of a same-scope ``def`` passed to ``jit`` by
       name, or
    3. is passed at a literal ``static_argnums`` position of a
       jit-built callable.

    Passing the scalar as a TRACED argument (or wrapping it
    ``jnp.int32(...)``) is the fix and stays quiet — traced values
    cannot force a retrace."""

    def __init__(self, mod: ModuleContext) -> None:
        self.mod = mod
        self.findings: list[Finding] = []
        #: call nodes already reported — _body walks a compound
        #: statement's whole subtree for sinks AND recurses into its
        #: nested bodies, so a sink inside an if/try would otherwise
        #: report once per nesting level
        self._seen_sinks: set[int] = set()

    def run(self) -> list[Finding]:
        self._scope(self.mod.tree.body)
        for node in ast.walk(self.mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scope(node.body)
        return self.findings

    # ── taint sources ────────────────────────────────────────────────

    @staticmethod
    def _root(node: ast.AST) -> str | None:
        while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
            node = node.func if isinstance(node, ast.Call) else node.value
        return node.id if isinstance(node, ast.Name) else None

    def _is_source(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Subscript):
            return self._root(node.value) in _REQUEST_NAMES
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "get":
                return self._root(f.value) in _REQUEST_NAMES
            if _dotted(f) == "json.loads":
                return True
        if isinstance(node, ast.Attribute):
            return (
                self._root(node.value) in ("request", "req")
                and node.attr in _REQUEST_ATTRS
            )
        return False

    def _tainted(self, expr: ast.AST, taint: set[str]) -> bool:
        for node in ast.walk(expr):
            if (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in taint
            ):
                return True
            if self._is_source(node):
                return True
        return False

    # ── scope walk ───────────────────────────────────────────────────

    def _scope(self, stmts: list[ast.stmt]) -> None:
        taint: set[str] = set()
        local_defs: dict[str, ast.AST] = {}
        static_jits: dict[str, tuple[int, ...]] = {}
        self._body(stmts, taint, local_defs, static_jits)

    @staticmethod
    def _walk_same_scope(node: ast.AST):
        """``ast.walk`` minus nested def/lambda subtrees: their assigns
        bind THEIR scope, not this one — letting them leak into the
        enclosing taint set produced confirmed false positives."""
        stack: list[ast.AST] = [node]
        while stack:
            cur = stack.pop()
            yield cur
            for child in ast.iter_child_nodes(cur):
                if isinstance(
                    child,
                    (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef),
                ):
                    continue
                stack.append(child)

    def _assigns(
        self,
        stmt: ast.stmt,
        taint: set[str],
        static_jits: dict[str, tuple[int, ...]],
    ) -> None:
        for node in self._walk_same_scope(stmt):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                if not isinstance(target, ast.Name):
                    continue
                jit_call = next(
                    (
                        sub
                        for sub in ast.walk(node.value)
                        if isinstance(sub, ast.Call)
                        and _is_jit_callable(sub.func)
                    ),
                    None,
                )
                if jit_call is not None:
                    positions = _literal_argnums(
                        jit_call, "static_argnums"
                    )
                    if positions:
                        static_jits[target.id] = positions
                if self._tainted(node.value, taint):
                    taint.add(target.id)
                else:
                    taint.discard(target.id)

    def _body(
        self,
        stmts: list[ast.stmt],
        taint: set[str],
        local_defs: dict[str, ast.AST],
        static_jits: dict[str, tuple[int, ...]],
    ) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local_defs[stmt.name] = stmt
                continue  # nested scopes get their own walk
            nested = [
                sub
                for attr in ("body", "orelse", "finalbody")
                for sub in (getattr(stmt, attr, None) or [])
                if isinstance(sub, ast.stmt)
            ] or list(getattr(stmt, "handlers", []) or [])
            if nested:
                # compound statement: only the HEADER expressions run at
                # this point in the statement order — sinks and assigns
                # inside the bodies are handled by the recursion below,
                # in their own order (an assign after a sink must not
                # retroactively taint it)
                for attr in ("test", "iter", "items"):
                    header = getattr(stmt, attr, None)
                    for part in header if isinstance(header, list) else (
                        [header] if header is not None else []
                    ):
                        self._sinks(part, taint, local_defs, static_jits)
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, attr, None)
                    if isinstance(sub, list) and sub and isinstance(
                        sub[0], ast.stmt
                    ):
                        self._body(sub, taint, local_defs, static_jits)
                for handler in getattr(stmt, "handlers", []) or []:
                    self._body(
                        handler.body, taint, local_defs, static_jits
                    )
                continue
            self._sinks(stmt, taint, local_defs, static_jits)
            self._assigns(stmt, taint, static_jits)

    # ── sinks ────────────────────────────────────────────────────────

    def _free_reads(self, fn: ast.AST, taint: set[str]) -> bool:
        """Does ``fn``'s body read a tainted name that is neither a
        parameter nor assigned locally?"""
        bound: set[str] = set()
        args = getattr(fn, "args", None)
        if args is not None:
            for a in (
                list(args.args)
                + list(getattr(args, "posonlyargs", []))
                + list(args.kwonlyargs)
            ):
                bound.add(a.arg)
            for extra in (args.vararg, args.kwarg):
                if extra is not None:
                    bound.add(extra.arg)
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Store
            ):
                bound.add(node.id)
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in taint
                and node.id not in bound
            ):
                return True
        return False

    def _sinks(
        self,
        stmt: ast.AST,
        taint: set[str],
        local_defs: dict[str, ast.AST],
        static_jits: dict[str, tuple[int, ...]],
    ) -> None:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call) or id(node) in self._seen_sinks:
                continue
            if _is_jit_callable(node.func):
                # sink 1: tainted name anywhere in the construction
                # (lambda defaults, partial bindings, closure captures)
                hit = any(
                    self._tainted(arg, taint)
                    for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]
                )
                # sink 2: jit(name) of a same-scope def with a tainted
                # free variable
                if not hit and node.args:
                    target = node.args[0]
                    name = target.id if isinstance(
                        target, ast.Name
                    ) else None
                    fn = local_defs.get(name or "")
                    if fn is not None and self._free_reads(fn, taint):
                        hit = True
                if hit:
                    self._seen_sinks.add(id(node))
                    self.findings.append(
                        self.mod.finding(
                            "GL105",
                            node,
                            "request-derived host scalar baked into a "
                            "jitted program's static surface — one "
                            "compile per distinct client value; pass "
                            "it as a traced argument or keep it a "
                            "host-side loop bound",
                        )
                    )
                continue
            # sink 3: tainted value at a static_argnums position
            fname = node.func.id if isinstance(node.func, ast.Name) else None
            positions = static_jits.get(fname or "")
            if not positions:
                continue
            for i in positions:
                if 0 <= i < len(node.args) and self._tainted(
                    node.args[i], taint
                ):
                    self._seen_sinks.add(id(node))
                    self.findings.append(
                        self.mod.finding(
                            "GL105",
                            node,
                            f"request-derived host scalar passed at "
                            f"static_argnums position {i} — one "
                            "compile per distinct client value; make "
                            "the argument traced or bucket it",
                        )
                    )
                    break


class TraceSafetyChecker(Checker):
    name = "GL1"
    description = "host side-effects / recompile hazards under jax.jit"
    codes = {
        "GL101": "host side-effect reachable inside a jitted function",
        "GL102": ".item() host sync inside a jitted function",
        "GL103": "jit-per-call / jit-in-loop recompile hazard",
        "GL104": "donated buffer (donate_argnums) read after the jitted "
        "call that consumed it",
        "GL105": "per-request host scalar baked into a traced program "
        "signature (one compile per distinct value)",
    }

    def __init__(self) -> None:
        # per-file state feeding the whole-run (cross-module) second
        # pass in finalize; keyed by rel_path
        self._indexes: dict[str, _FunctionIndex] = {}
        self._imports: dict[str, _ImportIndexBase] = {}
        self._mods: dict[str, ModuleContext] = {}
        self._roots: dict[str, list[ast.AST]] = {}
        #: (path, line, code) already reported by the module-local pass —
        #: the cross-module closure folds duplicates instead of double-
        #: reporting the same effect line
        self._reported: set[tuple[str, int, str]] = set()
        self._dotted_to_rel: dict[str, str] = {}

    def check_module(self, mod: ModuleContext) -> Iterable[Finding]:
        # the shared whole-program graph owns the symbol tables (built
        # once per run); a hand-built ModuleContext (no runner) falls
        # back to a local build so the checker stays unit-usable
        syms = (
            mod.runner.graph().modules.get(mod.rel_path)
            if mod.runner is not None
            else None
        )
        if syms is not None:
            index = syms.index
            imports = syms.imports
        else:
            index = _FunctionIndex()
            index.visit(mod.tree)
            imports = _ImportIndexBase(_package_of(mod.rel_path))
            imports.visit(mod.tree)
        self._indexes[mod.rel_path] = index
        self._imports[mod.rel_path] = imports
        self._mods[mod.rel_path] = mod

        # resolve "jit(name)" entries to def nodes where possible
        roots: list[ast.AST] = []
        for entry, _how in index.jitted:
            if isinstance(entry, str):
                short = entry.split(".")[-1]
                node = index.defs.get(entry)
                if node is None:
                    for name, cand in index.defs.items():
                        if name.split(".")[-1] == short:
                            node = cand
                            break
                if node is not None:
                    roots.append(node)
            else:
                roots.append(entry)
        self._roots[mod.rel_path] = roots

        findings: list[Finding] = []
        scans: dict[int, _TraceBodyScan] = {}

        def _scan(fn_node: ast.AST) -> _TraceBodyScan:
            key = id(fn_node)
            if key not in scans:
                scan = _TraceBodyScan()
                body = getattr(fn_node, "body", [])
                for stmt in body if isinstance(body, list) else [body]:
                    scan.visit(stmt)
                scans[key] = scan
            return scans[key]

        # reachability closure over module/class-local callees
        seen: set[int] = set()
        frontier = list(roots)
        while frontier:
            fn_node = frontier.pop()
            if id(fn_node) in seen:
                continue
            seen.add(id(fn_node))
            scan = _scan(fn_node)
            for node, code, msg in scan.effects:
                finding = mod.finding(
                    code, node, f"{msg} (reachable under jax.jit)"
                )
                findings.append(finding)
                self._reported.add(
                    (finding.path, finding.line, finding.code)
                )
            for callee in scan.calls:
                short = callee.split(".")[-1]
                for target_name, target in index.defs.items():
                    if target_name == callee or target_name.split(".")[
                        -1
                    ] in (callee, short):
                        if id(target) not in seen:
                            frontier.append(target)

        # GL103: jit(...)(...) immediately invoked, or jit built in a loop
        class _JitUse(ast.NodeVisitor):
            def __init__(self) -> None:
                self.loops = 0
                self.out: list[tuple[ast.AST, str]] = []

            def visit_Call(self, node: ast.Call) -> None:
                if isinstance(node.func, ast.Call) and _is_jit_callable(
                    node.func.func
                ):
                    self.out.append(
                        (
                            node,
                            "jit(...) called immediately — one trace+compile "
                            "per invocation",
                        )
                    )
                elif _is_jit_callable(node.func) and self.loops:
                    self.out.append(
                        (
                            node,
                            "jit(...) constructed inside a loop — retraces "
                            "every iteration",
                        )
                    )
                self.generic_visit(node)

            def _loop(self, node: ast.For | ast.While) -> None:
                self.loops += 1
                self.generic_visit(node)
                self.loops -= 1

            visit_For = _loop
            visit_While = _loop

        jit_use = _JitUse()
        jit_use.visit(mod.tree)
        for node, msg in jit_use.out:
            findings.append(mod.finding("GL103", node, msg))

        # GL104: donation-after-use liveness
        findings.extend(_DonationChecker(mod).run())
        # GL105: request-scalar-into-traced-signature taint
        findings.extend(_ScalarTaintChecker(mod).run())
        return findings

    # ── pass 2: whole-run cross-module reachability ──────────────────────

    def _resolve_callee(
        self, rel_path: str, callee: str
    ) -> list[tuple[str, ast.AST]]:
        """Where ``callee`` (a dotted call string seen in ``rel_path``)
        might be defined ACROSS the run's modules. Module-local
        resolution stays loose (the pass-1 behavior); cross-module
        resolution requires the receiver to be an actual import binding
        and the name to resolve in the target's function index — no
        short-name guessing across files."""
        out: list[tuple[str, ast.AST]] = []
        index = self._indexes.get(rel_path)
        imports = self._imports.get(rel_path)
        if index is None or imports is None:
            return out
        short = callee.split(".")[-1]
        for target_name, target in index.defs.items():
            if target_name == callee or target_name.split(".")[-1] in (
                callee, short,
            ):
                out.append((rel_path, target))
        dotted_to_rel = self._dotted_to_rel
        head, _, rest = callee.partition(".")
        if rest:
            # ``mod.fn(...)`` / ``mod.Class.meth(...)`` through an
            # import binding of ``mod``
            target_mod = imports.aliases.get(head)
            target_rel = dotted_to_rel.get(target_mod or "")
            if target_rel is not None:
                target_index = self._indexes.get(target_rel)
                if target_index is not None:
                    node = target_index.defs.get(
                        rest
                    ) or target_index.defs.get(rest.split(".")[-1])
                    if node is not None:
                        out.append((target_rel, node))
        else:
            # bare ``fn(...)`` bound by ``from mod import fn [as alias]``
            sym = imports.symbols.get(callee)
            if sym is not None:
                target_rel = dotted_to_rel.get(sym[0])
                if target_rel is not None:
                    target_index = self._indexes.get(target_rel)
                    if target_index is not None:
                        node = target_index.defs.get(sym[1])
                        if node is not None:
                            out.append((target_rel, node))
        return out

    def finalize(self, run) -> Iterable[Finding]:
        """The two-pass symbol-table closure: re-walk every jitted root,
        this time following calls THROUGH import bindings into other
        scanned modules (and onward — the frontier carries the module a
        function lives in, so its own imports resolve the next hop).
        Effects land in the file that contains them; anything pass 1
        already reported is folded."""
        self._dotted_to_rel = {
            _module_dotted(rel): rel for rel in self._indexes
        }
        findings: list[Finding] = []
        scans: dict[int, _TraceBodyScan] = {}

        def _scan(fn_node: ast.AST) -> _TraceBodyScan:
            key = id(fn_node)
            if key not in scans:
                scan = _TraceBodyScan()
                body = getattr(fn_node, "body", [])
                for stmt in body if isinstance(body, list) else [body]:
                    scan.visit(stmt)
                scans[key] = scan
            return scans[key]

        for root_rel, roots in self._roots.items():
            seen: set[tuple[str, int]] = set()
            frontier: list[tuple[str, ast.AST]] = [
                (root_rel, fn) for fn in roots
            ]
            while frontier:
                fn_rel, fn_node = frontier.pop()
                if (fn_rel, id(fn_node)) in seen:
                    continue
                seen.add((fn_rel, id(fn_node)))
                scan = _scan(fn_node)
                fn_mod = self._mods.get(fn_rel)
                if fn_mod is not None and fn_rel != root_rel:
                    # only FOREIGN effects are new — pass 1 owns the
                    # root module's local closure
                    for node, code, msg in scan.effects:
                        finding = fn_mod.finding(
                            code,
                            node,
                            f"{msg} (reachable under jax.jit via a "
                            f"cross-module call from {root_rel})",
                        )
                        key = (finding.path, finding.line, finding.code)
                        if key in self._reported:
                            continue
                        self._reported.add(key)
                        findings.append(finding)
                for callee in scan.calls:
                    for hop in self._resolve_callee(fn_rel, callee):
                        if (hop[0], id(hop[1])) not in seen:
                            frontier.append(hop)
        return findings
