"""GL2 whole-program concurrency analysis — gridconc.

The per-class GL2 rules (gl2_locks.py) see one class at a time; this
checker rides the shared whole-program graph
(:mod:`pygrid_tpu.analysis.graph`) to see the process: per-engine
device worker threads, the bounded WS handler pool, the daemon
telemetry/SLO/webhook threads, sub-aggregator fold locks, and the
aiohttp event loop all share state across module boundaries.

- **GL204** cross-module lock-order cycles. Lock identity is canonical
  ``(owner class, attr)`` (module-level locks: ``(file, <module>,
  name)``) and HELD SETS PROPAGATE THROUGH THE CALL GRAPH: a
  CycleManager method that calls ``telemetry.incr`` while holding
  ``_accum_lock`` creates the edge ``CycleManager._accum_lock →
  TelemetryBus._lock`` even though the acquisition is three modules
  away. A cycle in the resulting graph is a deadlock waiting for the
  right interleaving. Cycles entirely inside one class with no call
  hop are GL201's (reported there, not twice).
- **GL205** blocking/heavy work while a lock is held — the GL301–303
  pattern set plus the serde/frame-codec family, weighted by inferred
  execution domain: a lock-held blocking call reachable from the
  EVENT LOOP stalls every socket the process serves (error wording);
  on a worker/daemon/executor domain it stalls every thread that
  wants the lock (lock-hold latency). Condition ``wait()`` is not in
  the set (it releases the lock); the caller-holds-the-lock
  conventions (``*_locked``, "Under the lock" docstrings) count as
  held.
- **GL206** cross-domain mutation: a ``self._x`` written from ≥ 2
  inferred execution domains (loop / thread / daemon / executor) with
  no common lock across the write sites. Functions with no inferred
  domain contribute nothing (unreached code must not fabricate
  races); ``__init__`` is construction and exempt. When every write
  site holds *some* lock the rule still fires if the concrete held
  sets share no common lock (two locks guarding one attr is not
  protection); sites holding only the caller-held sentinel err quiet.
"""

from __future__ import annotations

import ast
from typing import Iterable

from pygrid_tpu.analysis.core import Checker, Finding
from pygrid_tpu.analysis.graph import (
    SENTINEL_HELD,
    FunctionNode,
    ProgramGraph,
    pretty_lock,
)

#: propagation fuel: (function, heldset) pairs visited per run — a
#: backstop far above anything a real repo produces, so pathological
#: fixtures cannot hang the gate
_MAX_VISITS = 200_000


def _concrete(held: frozenset) -> frozenset:
    return frozenset(l for l in held if l[2] != SENTINEL_HELD)


class ConcurrencyGraphChecker(Checker):
    name = "GL2"
    description = (
        "whole-program lock graph + domain-weighted lock-hold analysis"
    )
    codes = {
        "GL204": "cross-module lock-acquisition-order cycle (potential "
        "deadlock)",
        "GL205": "blocking/heavy call while a lock is held (lock-hold "
        "latency; event-loop stall when loop-reachable)",
        "GL206": "state written from ≥2 execution domains with no common "
        "lock",
    }

    def finalize(self, run) -> Iterable[Finding]:
        graph: ProgramGraph = run.graph()
        mods = {m.rel_path: m for m in run.modules}
        findings: list[Finding] = []
        findings.extend(self._lock_graph(graph, mods))
        findings.extend(self._cross_domain(graph, mods))
        return findings

    # ── GL204 + GL205: propagate held sets through the call graph ──────

    def _lock_graph(self, graph: ProgramGraph, mods) -> list[Finding]:
        findings: list[Finding] = []
        #: held lock -> {acquired lock: (mod, site node, provenance)}
        edges: dict[tuple, dict[tuple, tuple]] = {}
        #: (path, line, lock) GL205 sites already reported
        blocked_seen: set[tuple] = set()

        def _mod(rel):
            return mods.get(rel)

        def _note_blocking(
            fn: FunctionNode, site, held: frozenset, root: FunctionNode,
            chain: tuple = (),
        ) -> None:
            mod = _mod(fn.rel_path)
            if mod is None or not held:
                return
            locks = sorted(pretty_lock(l) for l in _concrete(held))
            if not locks:
                # only the caller-held sentinel: still a held lock
                locks = ["<caller-held lock>"]
            # one finding per blocking line, however many holders reach
            # it — the fix (move the work out / executor) is the same
            key = (fn.rel_path, site.node.lineno)
            if key in blocked_seen:
                return
            blocked_seen.add(key)
            domains = sorted(graph.domains_of(root.key))
            if "loop" in domains:
                weight = (
                    "EVENT-LOOP STALL — the holder is reachable from the "
                    "event loop"
                )
            elif domains:
                weight = (
                    f"lock-hold latency on the {'/'.join(domains)} domain"
                )
            else:
                weight = "lock-hold latency"
            via = (
                ""
                if root is fn
                else f" (held by '{root.pretty}' through the call graph)"
            )
            findings.append(
                mod.finding(
                    "GL205",
                    site.node,
                    f"{site.msg} while holding {', '.join(locks)}"
                    f"{via} — {weight}; move the heavy work outside "
                    "the lock or hand it to an executor",
                    witness=chain
                    + (
                        f"blocking call in {fn.pretty} at "
                        f"{fn.rel_path}:{site.node.lineno}",
                    ),
                )
            )

        def _note_edges(
            fn: FunctionNode, acq, held: frozenset, provenance: str
        ) -> None:
            mod = _mod(fn.rel_path)
            if mod is None:
                return
            for h in _concrete(held):
                if h == acq.lock and not acq.reentrant:
                    continue  # GL203's self-deadlock, owned there
                if h != acq.lock:
                    edges.setdefault(h, {}).setdefault(
                        acq.lock, (mod, acq.node, provenance)
                    )

        # direct (single-body) edges + direct blocking-under-lock
        for fn in graph.functions.values():
            for acq in fn.acquires:
                _note_edges(fn, acq, acq.held_before, "direct")
            for site in fn.blocking:
                if site.held:
                    _note_blocking(
                        fn, site, site.held, fn,
                        chain=(
                            "lock held in "
                            f"{fn.pretty} ({fn.rel_path})",
                        ),
                    )

        # call-propagated: BFS carrying (callee, held, root holder) plus
        # the witness call chain --explain renders
        seen: set[tuple] = set()
        frontier: list[tuple[tuple, frozenset, FunctionNode, tuple]] = []
        for fn in graph.functions.values():
            for call in fn.calls:
                if not call.held:
                    continue
                locks = sorted(
                    pretty_lock(l) for l in _concrete(call.held)
                ) or ["<caller-held lock>"]
                step = (
                    f"{fn.pretty} holds {', '.join(locks)} and calls "
                    f"into it at {fn.rel_path}:{call.node.lineno}"
                )
                for target in call.targets:
                    frontier.append((target, call.held, fn, (step,)))
        while frontier and len(seen) < _MAX_VISITS:
            key, held, root, chain = frontier.pop()
            state = (key, held)
            if state in seen:
                continue
            seen.add(state)
            fn = graph.functions.get(key)
            if fn is None:
                continue
            for acq in fn.acquires:
                _note_edges(
                    fn, acq, held, "call",
                )
            for site in fn.blocking:
                _note_blocking(fn, site, held | site.held, root, chain)
            for call in fn.calls:
                new_held = held | call.held
                step = (
                    f"which calls {call.dotted}() at "
                    f"{fn.rel_path}:{call.node.lineno}"
                )
                next_chain = (
                    chain + (step,) if len(chain) < 12 else chain
                )
                for target in call.targets:
                    frontier.append(
                        (target, frozenset(new_held), root, next_chain)
                    )

        # cycle detection over the merged edge graph; single-class
        # all-direct cycles belong to GL201
        color: dict[tuple, int] = {}
        stack: list[tuple] = []
        reported: set[frozenset] = set()

        def _dfs(lock: tuple) -> None:
            color[lock] = 1
            stack.append(lock)
            for nxt, (mod, site, provenance) in edges.get(
                lock, {}
            ).items():
                if color.get(nxt, 0) == 1:
                    cycle = stack[stack.index(nxt):] + [nxt]
                    key = frozenset(cycle)
                    if key not in reported:
                        reported.add(key)
                        owners = {(c[0], c[1]) for c in cycle}
                        provenances = {
                            edges[a][b][2]
                            for a, b in zip(cycle, cycle[1:])
                            if b in edges.get(a, {})
                        }
                        # one owner + no call hop = GL201 territory
                        if len(owners) > 1 or "call" in provenances:
                            pretty = " -> ".join(
                                pretty_lock(c) for c in cycle
                            )
                            witness = tuple(
                                f"{pretty_lock(a)} -> {pretty_lock(b)} "
                                f"acquired at "
                                f"{edges[a][b][0].rel_path}:"
                                f"{edges[a][b][1].lineno} "
                                f"({edges[a][b][2]} edge)"
                                for a, b in zip(cycle, cycle[1:])
                                if b in edges.get(a, {})
                            )
                            findings.append(
                                mod.finding(
                                    "GL204",
                                    site,
                                    "cross-module lock-order cycle: "
                                    f"{pretty} (deadlock under "
                                    "contention; edges follow the "
                                    "whole-program call graph)",
                                    witness=witness,
                                )
                            )
                elif color.get(nxt, 0) == 0:
                    _dfs(nxt)
            stack.pop()
            color[lock] = 2

        for lock in list(edges):
            if color.get(lock, 0) == 0:
                _dfs(lock)
        return findings

    # ── GL206: cross-domain unlocked mutation ──────────────────────────

    def _cross_domain(self, graph: ProgramGraph, mods) -> list[Finding]:
        findings: list[Finding] = []
        #: (class key, attr) -> list[(fn, site, domains)]
        writes: dict[tuple, list] = {}
        for fn in graph.functions.values():
            if fn.class_name is None or not fn.mutations:
                continue
            method = fn.qualname.rsplit(".", 1)[-1]
            if method in ("__init__", "__post_init__", "__new__"):
                continue  # construction is single-threaded by definition
            domains = graph.domains_of(fn.key)
            if not domains:
                continue  # unreached code must not fabricate races
            cls_key = (fn.rel_path, fn.class_name)
            if cls_key not in graph.classes:
                continue
            for site in fn.mutations:
                writes.setdefault((cls_key, site.attr), []).append(
                    (fn, site, domains)
                )
        for (cls_key, attr), sites in sorted(
            writes.items(), key=lambda kv: (kv[0][0][0], kv[0][1], kv[0][0][1])
        ):
            domains_union = set()
            for _fn, _site, domains in sites:
                domains_union |= domains
            if len(domains_union) < 2:
                continue
            unlocked = [
                (fn, site) for fn, site, _d in sites if not site.held
            ]
            if not unlocked:
                # every write holds SOME lock (possibly the caller-held
                # sentinel): common-lock analysis only over concrete
                # held sets; sentinel sites err quiet
                concrete_sites = [
                    (fn, site, _concrete(site.held))
                    for fn, site, _d in sites
                    if _concrete(site.held)
                ]
                if len(concrete_sites) < 2:
                    continue
                common = frozenset.intersection(
                    *(held for _fn, _site, held in concrete_sites)
                )
                if common:
                    continue
                witness_fn, witness, _held = concrete_sites[0]
            else:
                witness_fn, witness = unlocked[0]
            mod = mods.get(witness_fn.rel_path)
            if mod is None:
                continue
            by_domain = []
            for d in sorted(domains_union):
                holders = sorted(
                    {
                        fn.qualname
                        for fn, _site, doms in sites
                        if d in doms
                    }
                )[:2]
                by_domain.append(f"{d} via {', '.join(holders)}")
            findings.append(
                mod.finding(
                    "GL206",
                    witness.node,
                    f"'{cls_key[1]}.{attr}' is written from "
                    f"{len(domains_union)} execution domains "
                    f"({'; '.join(by_domain)}) with no common lock — "
                    "cross-domain race; guard every writer with one "
                    "lock or confine the state to one domain",
                )
            )
        return findings
