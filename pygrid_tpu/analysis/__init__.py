"""gridlint — the repo-native static-analysis suite.

Production stacks gate their invariants mechanically, not by reviewer
vigilance. This package is an AST-based checker framework purpose-built
for the failure modes THIS codebase has actually shipped (and caught by
luck): host side-effects reachable from jitted programs, lock/thread
hazards across the engine worker threads + telemetry bus + cycle
manager, event-loop-blocking calls inside async aiohttp handlers, and
contract drift between the wire/telemetry surface and its specs
(docs/WIRE.md tag bytes, docs/OBSERVABILITY.md metric families).

Run it:

    python -m pygrid_tpu.analysis pygrid_tpu/
    scripts/gridlint.sh

Checkers (see docs/ANALYSIS.md for the full rule catalogue):

- **GL1 trace-safety** (GL101/GL102/GL103) — host side-effects inside
  functions passed to ``jax.jit``/``pjit``; ``.item()`` host syncs;
  jit-per-call recompile hazards.
- **GL2 thread/lock discipline** (GL201/GL202/GL203 per class;
  GL204/GL205/GL206 whole-program) — lock-acquisition-order cycles,
  mutation of lock-protected ``self._`` state outside any ``with
  self._lock``, nested acquisition of an aliased non-reentrant lock;
  plus the gridconc pass over the shared run-wide call graph
  (``analysis/graph.py``): cross-module lock-order cycles with
  canonical ``(owner class, attr)`` identity, blocking/heavy calls
  while a lock is held weighted by inferred execution domain
  (event-loop / worker thread / daemon / executor), and state written
  from two domains with no common lock.
- **GL3 async hygiene** (GL301/GL302/GL303) — blocking calls
  (``time.sleep``, sync sockets/requests, ``Future.result()``,
  unbounded ``queue.get()``, megabyte serde) on the event loop inside
  ``async def`` handlers.
- **GL4 contract drift** (GL401/GL402/GL403/GL405/GL406) — bus metric
  families vs docs/OBSERVABILITY.md and the exporter HELP registry;
  wire tag bytes / subprotocol strings vs docs/WIRE.md (and their
  uniqueness); registered routes and dispatched WS events vs their
  docs. (GL404's typed-error heuristic is superseded by GL604.)
- **GL5 Pallas bounds** (GL501/GL502) — statically resolvable
  ``pallas_call`` tile/shape divisibility and index_map/grid arity.
- **GL6 dataflow & taint** (GL601/GL602/GL603/GL604, gridtaint —
  ``analysis/flow.py`` over the same whole-program graph) —
  interprocedural taint from privacy sources (worker payloads,
  ``request.json``, credentials, checkpoint bytes) into observability
  and egress sinks with sanitizer (redact/len/hash) recognition and
  full witness chains; resource acquire/release pairing on every
  explicit path; untyped-exception escape from protocol-boundary
  handlers through the whole call graph.

Per-line suppression: append ``# gridlint: disable=GL202`` (or a
comma-separated list, or ``all``) to any line of the offending
statement — suppressions are reported, never silent. Pre-existing
findings live in the committed baseline (``analysis/baseline.json``)
keyed ``(path, code) -> count`` with a justification note; a baseline
entry larger than reality is reported as *stale* so the allowance
shrinks as code heals.
"""

from __future__ import annotations

from pygrid_tpu.analysis.core import (  # noqa: F401
    Baseline,
    Finding,
    RunResult,
    default_baseline_path,
    run_checks,
)
from pygrid_tpu.analysis.checkers import ALL_CHECKERS  # noqa: F401

__all__ = [
    "ALL_CHECKERS",
    "Baseline",
    "Finding",
    "RunResult",
    "default_baseline_path",
    "run_checks",
]
