"""gridflow: interprocedural, flow-sensitive dataflow & taint analysis
over the whole-program graph.

PyGrid's value proposition is that private material — worker report and
diff payloads, model checkpoint bytes, ``request_key``/auth tokens —
stays private while flowing through a coordination plane that is now
wrapped in telemetry, flight dumps, SLO webhooks, and a wire protocol.
Every one of those is a potential exfiltration sink, and before this
module the redaction discipline was enforced by convention at exactly
one choke point (the flight recorder's key-based redactor). This engine
proves the discipline statically, FlowDroid/Pysa-style, riding the same
:class:`~pygrid_tpu.analysis.graph.ProgramGraph` the GL2 concurrency
rules use (one build per run — the tier-1 perf guard covers it too).

Three analyses share the graph:

- **Taint** (:class:`FlowEngine`) — forward propagation from declared
  *sources* (``request.json``, credential-keyed subscripts/``.get``,
  credential-named parameters, checkpoint loads) through assignments,
  calls/returns (per-function summaries, fixed point over the call
  graph), f-strings/``%``/``.format``, container literals, and
  ``self._x`` attribute stores, into declared *sinks* (logging,
  telemetry events/labels, flight-recorder ``note()``, webhook/HTTP
  bodies, outbound wire frames, WS/HTTP responses, exception messages)
  unless a *sanitizer* (the recorder's :func:`redact`, length markers
  via ``len``, hashing, numeric casts) kills the flow. Every finding
  carries the full witness chain — source, each call hop, sink.
- **Resources** (:func:`resource_findings`) — acquire/release pairing
  for the paged-KV :class:`BlockPool`, sockets, temp files, and
  non-``with`` lock acquires: every path out of the acquiring function
  (returns, explicit raises, fall-through) must release, store, or
  hand off the resource; ``try/finally`` and the repo's cleanup idioms
  (``close``/``release``/``retire``/``free``/``unlink``) are
  recognized, and ``x is None`` guards refine the path (a failed alloc
  is not a leak).
- **Exception escape** (:class:`ExceptionFlow`) — whole-program
  reachability of untyped raises: a ``raise ValueError`` (or any
  non-``PyGridError`` class) reachable from a route/WS handler entry
  point with no intervening catch on the call chain escapes the
  protocol boundary as an untyped 500. Catch coverage is computed per
  call site and per raise site from the enclosing ``try`` blocks
  (``except Exception`` covers everything; named handlers cover the
  name, its written bases, and the builtin hierarchy).

The GL6 checker family (``checkers/gl6_flow.py``) turns these into
GL601–GL604; ``--explain GL601`` prints the witness chains.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterable

from pygrid_tpu.analysis.graph import FunctionNode, ProgramGraph, dotted

#: fixed-point passes over the whole program — summaries are monotone
#: and settle in 2–3 passes on this repo; the cap is a safety net
_MAX_PASSES = 5

#: witness chains are capped so cyclic call graphs cannot grow them
_MAX_CHAIN = 16

# ── the declared source/sink/sanitizer surface ───────────────────────────

#: lowercase substrings marking a mapping KEY as credential-bearing.
#: Kept in lockstep with ``telemetry/recorder.py``'s ``_REDACT_KEYS``
#: (asserted by test_gridflow) — the redactor and the static analysis
#: must agree on what "credential-like" means.
CREDENTIAL_KEYS = (
    "token", "password", "secret", "request_key", "authorization",
    "auth", "jwt", "api_key", "private_key",
)

#: parameter names that ARE credentials wherever they appear — the
#: auth material this repo threads by name through worker/cycle code
CREDENTIAL_PARAMS = {
    "request_key", "auth_token", "api_key", "password", "jwt",
}

#: EXACT mapping keys whose values are model-scale private payloads
#: (worker reports/diffs, checkpoint blobs, dataset tensors)
PAYLOAD_KEYS = {
    "data", "diff", "diffs", "report", "params", "tensors",
    "checkpoint", "weights", "model_bytes",
}

#: callables whose RESULT is checkpoint/model bytes
CHECKPOINT_CALLS = {
    "load_encoded", "serialize_model_params", "serialize_plan",
}

#: receivers whose ``.json`` read is the request payload
REQUESTISH = {"request", "req", "message", "msg", "payload", "body"}

#: sanitizer callables: the value that comes out carries no private
#: content (redaction, length markers, hashes, numeric casts)
SANITIZER_NAMES = {
    "redact", "len", "int", "float", "bool", "hash", "abs", "round",
    "id", "type", "ord",
}
#: dotted heads whose whole namespace sanitizes (hashlib.sha256(x))
SANITIZER_MODULES = {"hashlib", "hmac"}

#: method names on UNRESOLVED receivers whose result derives from the
#: arguments (string formatting, codecs) — everything else unknown
#: keeps only the receiver's taint, so "the response of a call that
#: took a credential argument" does not become a credential
ARG_PROPAGATOR_METHODS = {
    "format", "join", "replace", "encode", "decode", "extend", "append",
    "update", "setdefault", "write", "writelines", "union", "fromhex",
}
#: bare builtins whose result derives from the arguments
ARG_PROPAGATOR_NAMES = {
    "str", "bytes", "bytearray", "repr", "list", "tuple", "set", "dict",
    "sorted", "reversed", "map", "filter", "zip", "enumerate", "next",
    "iter", "min", "max", "sum", "format", "vars", "print",
}
#: dotted heads whose namespace transforms-but-keeps content
ARG_PROPAGATOR_MODULES = {
    "json", "msgpack", "base64", "binascii", "pickle", "copy",
    "np", "numpy", "jnp", "jax",
}

_LOG_RECEIVERS = {"logger", "logging", "log"}
_LOG_METHODS = {
    "debug", "info", "warning", "warn", "error", "exception", "critical",
}
_BUS_RECEIVERS = {"telemetry", "bus", "BUS"}
_BUS_METHODS = {"incr", "observe", "record"}
_WS_SEND = {"send_str", "send_bytes", "send_json", "sendall"}
_HTTP_OUT = {"post", "put", "patch", "request"}

#: tags the GL601 privacy rule considers sensitive (credential flows
#: are GL602's everywhere, so they are classified there)
SENSITIVE_TAGS = {"payload", "checkpoint", "credential"}


@dataclass(frozen=True)
class Taint:
    """One tracked fact about a value: either a concrete source taint
    (``tag`` set — payload/credential/checkpoint) or a symbolic
    parameter taint (``param`` set) used to build function summaries.
    ``chain`` is the witness: human-readable steps from the origin."""

    tag: str | None
    origin: str
    chain: tuple = ()
    param: str | None = None

    @property
    def key(self) -> tuple:
        return (self.tag, self.param, self.origin)

    def extend(self, step: str) -> "Taint":
        if len(self.chain) >= _MAX_CHAIN:
            return self
        return Taint(self.tag, self.origin, self.chain + (step,), self.param)


#: env/taint-set representation: {taint.key: Taint} — one witness per
#: distinct (tag/param, origin), so sets stay small and monotone
TaintSet = dict


def _merge(*sets: TaintSet) -> TaintSet:
    out: TaintSet = {}
    for s in sets:
        for k, t in s.items():
            out.setdefault(k, t)
    return out


@dataclass(frozen=True)
class SinkSpec:
    kind: str  # logging | metric | note | http_out | wire | response
    category: str  # "obs" (observability) | "egress"
    desc: str


@dataclass
class SinkFlow:
    """Summary entry: this function passes ``param`` into a sink (its
    own, or transitively through a callee)."""

    param: str
    sink: SinkSpec
    site: tuple  # (rel_path, line) — dedupe/site identity
    node: ast.AST
    rel_path: str
    chain: tuple  # steps from the param to the sink


@dataclass
class FlowHit:
    """A concrete source→sink flow (a GL601/GL602 finding candidate)."""

    tag: str
    origin: str
    sink: SinkSpec
    node: ast.AST
    rel_path: str
    chain: tuple

    @property
    def site(self) -> tuple:
        return (self.rel_path, getattr(self.node, "lineno", 0))


@dataclass
class Summary:
    """One function's interprocedural surface, grown monotonically to a
    fixed point."""

    param_to_return: set = field(default_factory=set)
    #: tag -> Taint introduced inside that reaches the return value
    source_returns: dict = field(default_factory=dict)
    #: (param, sink site, kind) -> SinkFlow
    param_sinks: dict = field(default_factory=dict)
    #: param -> {((rel, class), attr)} — fields the param is stored to
    #: (``self._x = param``); callers replay their concrete taints onto
    #: the class-attr map (field-sensitive param summaries)
    param_to_fields: dict = field(default_factory=dict)

    def shape(self) -> tuple:
        return (
            frozenset(self.param_to_return),
            frozenset(self.source_returns),
            frozenset(self.param_sinks),
            frozenset(
                (p, f)
                for p, fields in self.param_to_fields.items()
                for f in fields
            ),
        )


def _fn_loc(fn: FunctionNode) -> str:
    return f"{fn.rel_path}:{getattr(fn.node, 'lineno', 0)}"


def _params_of(fn: FunctionNode) -> list[str]:
    args = fn.node.args
    names = [
        a.arg
        for a in (
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        )
    ]
    return names


def _is_credential_key(key: str) -> bool:
    low = key.lower()
    return any(m in low for m in CREDENTIAL_KEYS)


# ── the per-function taint interpreter ───────────────────────────────────


class _FnFlow:
    """One statement-ordered pass over one function body, against the
    current summaries. Flow-sensitive for locals, flow-insensitive for
    ``self._x`` attribute stores (class-attr taint map shared across
    methods)."""

    def __init__(self, engine: "FlowEngine", fn: FunctionNode) -> None:
        self.engine = engine
        self.graph = engine.graph
        self.fn = fn
        self.summary = Summary()
        self.hits: list[FlowHit] = []
        params = _params_of(fn)
        self.params = set(params)
        self.env: dict[str, TaintSet] = {}
        for p in params:
            t = Taint(None, f"parameter '{p}' of {fn.pretty}", param=p)
            self.env[p] = {t.key: t}
            if p in CREDENTIAL_PARAMS:
                s = Taint(
                    "credential",
                    f"credential parameter '{p}' of {fn.pretty}",
                )
                self.env[p][s.key] = s

    # ── driving ─────────────────────────────────────────────────────────

    def run(self) -> None:
        body = getattr(self.fn.node, "body", [])
        # two passes over the body: loop-carried and later-defined
        # taint (a helper assigned below its use site) settles on the
        # second — cheap, and enough for lint-grade precision
        self._exec(body)
        self._exec(body)

    def _exec(self, stmts: list) -> None:
        for stmt in stmts:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs are their own FunctionNodes
        if isinstance(stmt, ast.Assign):
            taints = self._eval(stmt.value)
            for target in stmt.targets:
                self._bind(target, taints)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._bind(stmt.target, self._eval(stmt.value))
        elif isinstance(stmt, ast.AugAssign):
            taints = self._eval(stmt.value)
            if isinstance(stmt.target, ast.Name):
                self.env[stmt.target.id] = _merge(
                    self.env.get(stmt.target.id, {}), taints
                )
            else:
                self._bind(stmt.target, taints)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._record_return(self._eval(stmt.value))
        elif isinstance(stmt, ast.Raise):
            self._raise_sink(stmt)
        elif isinstance(stmt, ast.Expr):
            self._eval(stmt.value)
        elif isinstance(stmt, (ast.If,)):
            self._eval(stmt.test)
            self._exec(stmt.body)
            self._exec(stmt.orelse)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            iter_taints = self._eval(stmt.iter)
            self._bind(stmt.target, iter_taints)
            self._exec(stmt.body)
            self._exec(stmt.orelse)
        elif isinstance(stmt, ast.While):
            self._eval(stmt.test)
            self._exec(stmt.body)
            self._exec(stmt.orelse)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                t = self._eval(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, t)
            self._exec(stmt.body)
        elif isinstance(stmt, ast.Try):
            self._exec(stmt.body)
            for handler in stmt.handlers:
                self._exec(handler.body)
            self._exec(stmt.orelse)
            self._exec(stmt.finalbody)
        elif isinstance(stmt, (ast.Delete, ast.Assert)):
            pass
        # remaining statement kinds carry no dataflow we model

    def _bind(self, target: ast.AST, taints: TaintSet) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = dict(taints)
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._bind(el, taints)  # container-insensitive
            return
        if isinstance(target, ast.Starred):
            self._bind(target.value, taints)
            return
        if isinstance(target, ast.Subscript):
            # d[k] = tainted taints the container name too
            if isinstance(target.value, ast.Name):
                self.env[target.value.id] = _merge(
                    self.env.get(target.value.id, {}), taints
                )
            elif isinstance(target.value, ast.Attribute):
                self._store_attr(target.value, taints)
            return
        if isinstance(target, ast.Attribute):
            self._store_attr(target, taints)

    def _store_attr(self, target: ast.Attribute, taints: TaintSet) -> None:
        """``self._x = tainted``: record on the class-attr map so every
        method's reads observe it (flow-insensitive field taint).
        Symbolic param taints become ``param_to_fields`` summary entries
        — each caller replays its own concrete argument taints onto the
        field (field-sensitive param summaries)."""
        if not (
            isinstance(target.value, ast.Name)
            and target.value.id in ("self", "cls")
            and self.fn.class_name is not None
        ):
            return
        key = ((self.fn.rel_path, self.fn.class_name), target.attr)
        for t in taints.values():
            if t.param is not None:
                self.summary.param_to_fields.setdefault(
                    t.param, set()
                ).add(key)
        concrete = {
            k: t.extend(
                f"stored to self.{target.attr} in {self.fn.pretty}"
            )
            for k, t in taints.items()
            if t.tag is not None
        }
        if not concrete:
            return
        store = self.engine.attr_taints.setdefault(key, {})
        before = len(store)
        for k, t in concrete.items():
            store.setdefault(k, t)
        if len(store) != before:
            self.engine.attrs_changed = True

    def _record_return(self, taints: TaintSet) -> None:
        for t in taints.values():
            if t.param is not None:
                self.summary.param_to_return.add(t.param)
            elif t.tag is not None:
                self.summary.source_returns.setdefault(
                    t.key,
                    t.extend(f"returned by {self.fn.pretty}"),
                )

    # ── expression evaluation ───────────────────────────────────────────

    def _eval(self, expr: ast.AST) -> TaintSet:
        if isinstance(expr, ast.Name):
            return self.env.get(expr.id, {})
        if isinstance(expr, ast.Await):
            return self._eval(expr.value)
        if isinstance(expr, ast.Call):
            return self._call(expr)
        if isinstance(expr, ast.Attribute):
            return self._attribute(expr)
        if isinstance(expr, ast.Subscript):
            return self._subscript(expr)
        if isinstance(expr, ast.BinOp):
            return _merge(self._eval(expr.left), self._eval(expr.right))
        if isinstance(expr, ast.JoinedStr):
            out: TaintSet = {}
            for v in expr.values:
                if isinstance(v, ast.FormattedValue):
                    out = _merge(out, self._eval(v.value))
            return out
        if isinstance(expr, ast.FormattedValue):
            return self._eval(expr.value)
        if isinstance(expr, (ast.List, ast.Tuple, ast.Set)):
            out = {}
            for el in expr.elts:
                out = _merge(out, self._eval(el))
            return out
        if isinstance(expr, ast.Dict):
            out = {}
            for k, v in zip(expr.keys, expr.values):
                if k is not None:
                    out = _merge(out, self._eval(k))
                out = _merge(out, self._eval(v))
            return out
        if isinstance(expr, ast.IfExp):
            self._eval(expr.test)
            return _merge(self._eval(expr.body), self._eval(expr.orelse))
        if isinstance(expr, ast.BoolOp):
            out = {}
            for v in expr.values:
                out = _merge(out, self._eval(v))
            return out
        if isinstance(expr, ast.Compare):
            self._eval(expr.left)
            for c in expr.comparators:
                self._eval(c)
            return {}  # a bool comparison result carries no content
        if isinstance(expr, ast.UnaryOp):
            inner = self._eval(expr.operand)
            return {} if isinstance(expr.op, ast.Not) else inner
        if isinstance(expr, ast.Starred):
            return self._eval(expr.value)
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            for gen in expr.generators:
                self._bind(gen.target, self._eval(gen.iter))
            return self._eval(expr.elt)
        if isinstance(expr, ast.DictComp):
            for gen in expr.generators:
                self._bind(gen.target, self._eval(gen.iter))
            return _merge(self._eval(expr.key), self._eval(expr.value))
        if isinstance(expr, ast.NamedExpr):
            t = self._eval(expr.value)
            self._bind(expr.target, t)
            return t
        if isinstance(expr, ast.Slice):
            return {}
        return {}

    def _attribute(self, expr: ast.Attribute) -> TaintSet:
        # source: request.json (aiohttp's awaited read or a cached prop)
        if (
            expr.attr == "json"
            and isinstance(expr.value, ast.Name)
            and expr.value.id in REQUESTISH
        ):
            t = Taint(
                "payload",
                f"{expr.value.id}.json at "
                f"{self.fn.rel_path}:{expr.lineno}",
            )
            return {t.key: t}
        # self._x reads observe the class-attr taint map (via the MRO)
        if (
            isinstance(expr.value, ast.Name)
            and expr.value.id in ("self", "cls")
            and self.fn.class_name is not None
        ):
            out: TaintSet = {}
            for cls_key in self.graph.mro(
                (self.fn.rel_path, self.fn.class_name)
            ):
                stored = self.engine.attr_taints.get((cls_key, expr.attr))
                if stored:
                    out = _merge(out, stored)
            return out
        return self._eval(expr.value)

    def _subscript(self, expr: ast.Subscript) -> TaintSet:
        base = self._eval(expr.value)
        key = expr.slice
        if isinstance(key, ast.Constant) and isinstance(key.value, str):
            src = self._keyed_source(key.value, expr)
            if src is not None:
                return _merge(base, src)
        else:
            self._eval(key)
        return base

    def _keyed_source(self, key: str, node: ast.AST) -> TaintSet | None:
        loc = f"{self.fn.rel_path}:{getattr(node, 'lineno', 0)}"
        if _is_credential_key(key):
            t = Taint("credential", f"credential field {key!r} at {loc}")
            return {t.key: t}
        if key in PAYLOAD_KEYS:
            t = Taint("payload", f"payload field {key!r} at {loc}")
            return {t.key: t}
        return None

    # ── calls: sanitizers, sources, sinks, summaries ────────────────────

    def _call(self, call: ast.Call) -> TaintSet:
        d = dotted(call.func)
        tail = d.split(".")[-1] if d else None
        head = d.split(".")[0] if d else None

        arg_taints = [self._eval(a) for a in call.args]
        kw_taints = {
            (kw.arg or "**"): self._eval(kw.value) for kw in call.keywords
        }

        # ``.get("key", ...)`` keyed source on any receiver
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "get"
            and call.args
            and isinstance(call.args[0], ast.Constant)
            and isinstance(call.args[0].value, str)
        ):
            src = self._keyed_source(call.args[0].value, call)
            if src is not None:
                recv = self._eval(call.func.value)
                return _merge(recv, src)

        # request.json() spelled as a call
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "json"
            and isinstance(call.func.value, ast.Name)
            and call.func.value.id in REQUESTISH
        ):
            t = Taint(
                "payload",
                f"{call.func.value.id}.json() at "
                f"{self.fn.rel_path}:{call.lineno}",
            )
            return {t.key: t}

        # sanitizers kill the flow
        if tail in SANITIZER_NAMES or head in SANITIZER_MODULES:
            return {}

        # declared sinks observe the argument taints
        sink = self._sink_of(call, tail)
        if sink is not None:
            self._check_sink(call, sink, arg_taints, kw_taints)

        # checkpoint-bytes sources
        if tail in CHECKPOINT_CALLS:
            t = Taint(
                "checkpoint",
                f"{tail}() checkpoint bytes at "
                f"{self.fn.rel_path}:{call.lineno}",
            )
            return {t.key: t}

        # resolved callee: apply interprocedural summaries
        targets = ()
        if d is not None:
            targets = self.graph.resolve_call(
                self.fn.rel_path,
                self.fn.class_name,
                d,
                None,
            )
        if targets:
            return self._apply_summaries(call, d, targets, arg_taints,
                                         kw_taints)

        # unresolved call: a method on a tainted object derives from it
        # (receiver taint always flows); argument taint flows only
        # through known string/codec propagators — an unknown callee's
        # RESULT does not inherit its arguments' secrets
        out: TaintSet = {}
        args_flow = False
        if isinstance(call.func, ast.Attribute):
            out = _merge(out, self._eval(call.func.value))
            args_flow = call.func.attr in ARG_PROPAGATOR_METHODS
        elif isinstance(call.func, ast.Name):
            args_flow = call.func.id in ARG_PROPAGATOR_NAMES
        if head in ARG_PROPAGATOR_MODULES:
            args_flow = True
        if args_flow:
            for t in arg_taints:
                out = _merge(out, t)
            for t in kw_taints.values():
                out = _merge(out, t)
        return out

    def _apply_summaries(
        self,
        call: ast.Call,
        d: str,
        targets: tuple,
        arg_taints: list,
        kw_taints: dict,
    ) -> TaintSet:
        result: TaintSet = {}
        loc = f"{self.fn.rel_path}:{call.lineno}"
        for key in targets:
            callee = self.graph.functions.get(key)
            summary = self.engine.summaries.get(key)
            if callee is None or summary is None:
                continue
            params = _params_of(callee)
            # a method called through a receiver maps args after self
            offset = 0
            if (
                callee.class_name is not None
                and isinstance(call.func, ast.Attribute)
                and params
                and params[0] in ("self", "cls")
            ):
                offset = 1
            bound: list[tuple[str, TaintSet]] = []
            for i, taints in enumerate(arg_taints):
                idx = i + offset
                if idx < len(params):
                    bound.append((params[idx], taints))
            for name, taints in kw_taints.items():
                if name in params:
                    bound.append((name, taints))
            step = f"passed to {callee.pretty}() at {loc}"
            for pname, taints in bound:
                if not taints:
                    continue
                if pname in summary.param_to_return:
                    for t in taints.values():
                        e = t.extend(
                            f"through {callee.pretty}() at {loc}"
                        )
                        result.setdefault(e.key, e)
                for flow in summary.param_sinks.values():
                    if flow.param != pname:
                        continue
                    for t in taints.values():
                        if t.param is not None:
                            # transitive: OUR param reaches a sink
                            skey = (t.param, flow.site, flow.sink.kind)
                            self.summary.param_sinks.setdefault(
                                skey,
                                SinkFlow(
                                    param=t.param,
                                    sink=flow.sink,
                                    site=flow.site,
                                    node=flow.node,
                                    rel_path=flow.rel_path,
                                    chain=t.chain + (step,) + flow.chain,
                                ),
                            )
                        elif t.tag is not None:
                            self.hits.append(
                                FlowHit(
                                    tag=t.tag,
                                    origin=t.origin,
                                    sink=flow.sink,
                                    node=flow.node,
                                    rel_path=flow.rel_path,
                                    chain=t.chain + (step,) + flow.chain,
                                )
                            )
                for fkey in summary.param_to_fields.get(pname, ()):
                    # the callee stores this param to a field: replay
                    # OUR concrete taints onto the class-attr map, and
                    # carry symbolic ones up as our own field summary
                    for t in taints.values():
                        if t.param is not None:
                            self.summary.param_to_fields.setdefault(
                                t.param, set()
                            ).add(fkey)
                        elif t.tag is not None:
                            e = t.extend(
                                f"stored to {fkey[0][1]}.{fkey[1]} "
                                f"via {callee.pretty}() at {loc}"
                            )
                            store = self.engine.attr_taints.setdefault(
                                fkey, {}
                            )
                            if e.key not in store:
                                store[e.key] = e
                                self.engine.attrs_changed = True
            for t in summary.source_returns.values():
                e = t.extend(f"returned to {self.fn.pretty} at {loc}")
                result.setdefault(e.key, e)
        return result

    # ── sink recognition ────────────────────────────────────────────────

    def _sink_of(self, call: ast.Call, tail: str | None) -> SinkSpec | None:
        fn = call.func
        if isinstance(fn, ast.Attribute):
            recv = dotted(fn.value) or ""
            recv_tail = recv.split(".")[-1]
            if recv_tail in _LOG_RECEIVERS and fn.attr in _LOG_METHODS:
                return SinkSpec("logging", "obs", f"{recv}.{fn.attr}()")
            if recv_tail in _BUS_RECEIVERS and fn.attr in _BUS_METHODS:
                return SinkSpec(
                    "metric", "obs", f"telemetry {fn.attr}() label/field"
                )
            if fn.attr == "note":
                return SinkSpec(
                    "note", "obs", "flight-recorder note() field"
                )
            if recv_tail == "requests" and fn.attr in _HTTP_OUT:
                return SinkSpec(
                    "http_out", "obs", f"outbound HTTP {recv}.{fn.attr}()"
                )
            if fn.attr == "urlopen":
                return SinkSpec("http_out", "obs", "outbound urlopen()")
            if fn.attr in _WS_SEND:
                return SinkSpec(
                    "wire", "egress", f"outbound WS {fn.attr}()"
                )
            if fn.attr == "json_response":
                return SinkSpec(
                    "response", "egress", "HTTP json_response() body"
                )
        elif isinstance(fn, ast.Name):
            if fn.id in ("incr", "observe", "record"):
                return SinkSpec(
                    "metric", "obs", f"telemetry {fn.id}() label/field"
                )
            if fn.id == "json_response":
                return SinkSpec(
                    "response", "egress", "HTTP json_response() body"
                )
        if tail == "encode_frame":
            return SinkSpec("wire", "egress", "outbound wire frame")
        return None

    def _check_sink(
        self,
        call: ast.Call,
        sink: SinkSpec,
        arg_taints: list,
        kw_taints: dict,
    ) -> None:
        # the metric-family literal (arg 0 of incr/observe/record) is a
        # name, not a value — skip it
        args = arg_taints[1:] if sink.kind == "metric" else arg_taints
        flows: list[TaintSet] = list(args)
        for name, taints in kw_taints.items():
            if sink.kind == "note" and name != "**" and _is_credential_key(
                name
            ):
                # the dump-time key redactor covers this field — that
                # is precisely the sanctioned way to note a credential
                continue
            flows.append(taints)
        for taints in flows:
            for t in taints.values():
                self._observe_at_sink(t, sink, call)

    def _raise_sink(self, stmt: ast.Raise) -> None:
        if stmt.exc is None:
            return
        exc = stmt.exc
        sink = SinkSpec("exception", "egress", "exception message")
        if isinstance(exc, ast.Call):
            for a in exc.args:
                for t in self._eval(a).values():
                    self._observe_at_sink(t, sink, stmt)
            for kw in exc.keywords:
                for t in self._eval(kw.value).values():
                    self._observe_at_sink(t, sink, stmt)

    def _observe_at_sink(
        self, t: Taint, sink: SinkSpec, node: ast.AST
    ) -> None:
        site = (self.fn.rel_path, getattr(node, "lineno", 0))
        if t.param is not None:
            skey = (t.param, site, sink.kind)
            self.summary.param_sinks.setdefault(
                skey,
                SinkFlow(
                    param=t.param,
                    sink=sink,
                    site=site,
                    node=node,
                    rel_path=self.fn.rel_path,
                    chain=(
                        f"reaches {sink.desc} in {self.fn.pretty} at "
                        f"{self.fn.rel_path}:{getattr(node, 'lineno', 0)}",
                    ),
                ),
            )
        elif t.tag is not None:
            self.hits.append(
                FlowHit(
                    tag=t.tag,
                    origin=t.origin,
                    sink=sink,
                    node=node,
                    rel_path=self.fn.rel_path,
                    chain=t.chain
                    + (
                        f"reaches {sink.desc} in {self.fn.pretty} at "
                        f"{self.fn.rel_path}:{getattr(node, 'lineno', 0)}",
                    ),
                )
            )


# ── the engine: fixed point over the call graph ──────────────────────────


class FlowEngine:
    """Builds per-function taint summaries to a fixed point and collects
    concrete source→sink flows with witness chains."""

    def __init__(self, graph: ProgramGraph) -> None:
        self.graph = graph
        self.summaries: dict[tuple, Summary] = {
            key: Summary() for key in graph.functions
        }
        #: (class key, attr) -> TaintSet — the attribute-store channel
        self.attr_taints: dict[tuple, TaintSet] = {}
        self.attrs_changed = False
        self.hits: list[FlowHit] = []
        self._run()

    def _run(self) -> None:
        for _ in range(_MAX_PASSES):
            changed = False
            self.attrs_changed = False
            hits: list[FlowHit] = []
            for key, fn in self.graph.functions.items():
                ff = _FnFlow(self, fn)
                ff.run()
                if ff.summary.shape() != self.summaries[key].shape():
                    changed = True
                self.summaries[key] = ff.summary
                hits.extend(ff.hits)
            self.hits = hits
            if not changed and not self.attrs_changed:
                break
        # dedupe: ONE finding per (sink site, tag) — the shortest-chain
        # witness represents however many origins reach the line (the
        # fix is the same), so baseline counts stay stable as code
        # grows new callers
        seen: set[tuple] = set()
        unique: list[FlowHit] = []
        for h in sorted(
            self.hits, key=lambda h: (h.rel_path, h.site[1], len(h.chain))
        ):
            k = (h.site, h.tag)
            if k not in seen:
                seen.add(k)
                unique.append(h)
        self.hits = unique


# ── GL603: resource acquire/release pairing ──────────────────────────────


@dataclass
class _Resource:
    kind: str
    node: ast.AST
    names: tuple  # local names bound to it
    open: bool = True
    escaped: bool = False


_RELEASE_METHODS = {
    "release", "close", "retire", "free", "shutdown", "unlink",
    "remove", "replace", "_fail_all", "cleanup",
}


def _acquire_of(value: ast.AST) -> str | None:
    """The resource KIND if ``value`` is an acquire expression."""
    if not isinstance(value, ast.Call):
        return None
    d = dotted(value.func)
    if d is None:
        return None
    tail = d.split(".")[-1]
    recv = d.rsplit(".", 1)[0] if "." in d else ""
    if tail == "alloc" and "pool" in recv.lower():
        return "pool blocks"
    if d in ("socket.socket", "socket.create_connection"):
        return "socket"
    if d == "tempfile.mkstemp":
        return "temp file"
    if d == "tempfile.NamedTemporaryFile":
        for kw in value.keywords:
            if (
                kw.arg == "delete"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is False
            ):
                return "temp file"
        return None
    return None


class _ResourceWalk:
    """Intra-procedural path walk for acquire/release pairing. Exits
    modeled: returns, explicit raises, fall-through — and, when an
    :class:`ExceptionFlow` is supplied, IMPLICIT raises: a statement
    calling a resolved callee whose untyped-exception escape set is not
    covered by an enclosing ``try`` can blow through the frame, so an
    open unprotected resource leaks there too. Unresolvable callees err
    quiet rather than flooding."""

    def __init__(
        self, fn: FunctionNode, exc_flow: "ExceptionFlow | None" = None
    ) -> None:
        self.fn = fn
        self.exc_flow = exc_flow
        #: (lineno, col) -> resolved callee keys, from the graph's call
        #: edges — drives the implicit-raise check
        self._call_targets = {
            (c.node.lineno, c.node.col_offset): c.targets
            for c in fn.calls
        }
        self.findings: list[tuple[ast.AST, str, str]] = []  # node, kind, why
        self._counter = 0
        #: resource keys already reported — clones share keys, so a
        #: leak reported in one branch is never re-reported when the
        #: join's merge re-opens the resource for the OTHER path (one
        #: report per acquire keeps baseline allowances stable)
        self._reported: set[int] = set()

    # ── implicit exception propagation ──────────────────────────────────

    def _implicit_raise_via(self, stmt: ast.stmt) -> str | None:
        """A callee in ``stmt`` whose uncovered untyped escape can blow
        through this frame, or None. Catch coverage at the call site is
        the same enclosing-try model GL604 uses."""
        if self.exc_flow is None:
            return None
        graph = self.exc_flow.graph
        covers = self.exc_flow._covers.get(self.fn.key, {})
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            targets = self._call_targets.get(
                (node.lineno, node.col_offset)
            )
            if not targets:
                continue
            active = covers.get((node.lineno, node.col_offset), [])
            for target in targets:
                callee = graph.functions.get(target)
                if callee is None:
                    continue
                if callee.is_async and not self.fn.is_async:
                    continue  # only scheduled here, raises at the await
                for exc in self.exc_flow.escapes.get(target, ()):
                    if not self.exc_flow._covered(
                        exc, active, self.fn.rel_path
                    ):
                        return f"{callee.qualname}() (raises {exc})"
        return None

    def _implicit_leaks(
        self, stmt: ast.stmt, state: dict, protected: frozenset
    ) -> None:
        via = self._implicit_raise_via(stmt)
        if via is None:
            return
        for key, res in state.items():
            if res.open and not res.escaped and not (
                set(res.names) & protected
            ) and key not in self._reported:
                self._reported.add(key)
                self.findings.append(
                    (
                        res.node,
                        res.kind,
                        f"leaks when {via} propagates through this "
                        "frame (implicit exception path, no try/finally "
                        "release)",
                    )
                )
                res.open = False

    def run(self) -> list[tuple[ast.AST, str, str]]:
        state: dict[int, _Resource] = {}
        self._walk(getattr(self.fn.node, "body", []), state, frozenset())
        self._leaks(state, "falls off the end of the function")
        return self.findings

    # ── helpers ─────────────────────────────────────────────────────────

    def _leaks(self, state: dict, why: str) -> None:
        for key, res in state.items():
            if res.open and not res.escaped and key not in self._reported:
                self._reported.add(key)
                self.findings.append((res.node, res.kind, why))
                res.open = False

    def _names_in(self, expr: ast.AST) -> set[str]:
        return {
            n.id for n in ast.walk(expr) if isinstance(n, ast.Name)
        }

    def _release_names(self, stmts: list) -> set[str]:
        """Names released anywhere in ``stmts`` (a finally body): a
        shallow scan — finally is the cleanup idiom, it is small."""
        out: set[str] = set()
        for stmt in stmts:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    out |= self._release_in_call(node)
        return out

    def _release_in_call(self, call: ast.Call) -> set[str]:
        """Local names this call releases: ``name.close()`` /
        ``pool.release(name)`` / ``self._lock.release()`` /
        ``os.unlink(path)`` — the receiver (a name OR a dotted chain,
        matching the acquire spelling) and every name argument."""
        out: set[str] = set()
        if not isinstance(call.func, ast.Attribute):
            return out
        if call.func.attr not in _RELEASE_METHODS:
            return out
        recv = dotted(call.func.value)
        if recv is not None:
            out.add(recv)
        for a in call.args:
            if isinstance(a, ast.Name):
                out.add(a.id)
            elif isinstance(a, ast.Starred) and isinstance(
                a.value, ast.Name
            ):
                out.add(a.value.id)
        return out

    def _apply_release(self, state: dict, names: set[str]) -> None:
        for res in state.values():
            if res.open and any(n in names for n in res.names):
                res.open = False

    def _apply_escapes(self, state: dict, names: set[str]) -> None:
        for res in state.values():
            if res.open and any(n in names for n in res.names):
                res.escaped = True

    def _none_guard(self, test: ast.AST) -> tuple[str, bool] | None:
        """``x is None``/``not x`` → (name, True): x is ABSENT on the
        then-branch. ``x is not None``/``x`` → (name, False)."""
        if isinstance(test, ast.Compare) and isinstance(
            test.left, ast.Name
        ) and len(test.ops) == 1 and len(test.comparators) == 1:
            comp = test.comparators[0]
            if isinstance(comp, ast.Constant) and comp.value is None:
                if isinstance(test.ops[0], ast.Is):
                    return (test.left.id, True)
                if isinstance(test.ops[0], ast.IsNot):
                    return (test.left.id, False)
        if isinstance(test, ast.UnaryOp) and isinstance(
            test.op, ast.Not
        ) and isinstance(test.operand, ast.Name):
            return (test.operand.id, True)
        if isinstance(test, ast.Name):
            return (test.id, False)
        return None

    def _drop_name(self, state: dict, name: str) -> dict:
        out = {}
        for k, res in state.items():
            if name in res.names:
                continue  # the guard proved the acquire failed
            out[k] = res
        return out

    @staticmethod
    def _clone(state: dict) -> dict:
        return {
            k: _Resource(
                r.kind, r.node, r.names, r.open, r.escaped
            )
            for k, r in state.items()
        }

    def _merge_into(self, state: dict, branches: list[dict]) -> None:
        """After control-flow joins: a resource is closed/escaped only
        when EVERY branch that still tracks it agrees."""
        state.clear()
        all_keys: set[int] = set()
        for b in branches:
            all_keys |= set(b)
        for k in all_keys:
            versions = [b[k] for b in branches if k in b]
            state[k] = _Resource(
                versions[0].kind,
                versions[0].node,
                versions[0].names,
                open=any(v.open for v in versions),
                escaped=all(
                    v.escaped or not v.open for v in versions
                )
                and any(v.escaped for v in versions),
            )

    # ── the walk ────────────────────────────────────────────────────────

    def _walk(
        self, stmts: list, state: dict, protected: frozenset
    ) -> None:
        for stmt in stmts:
            self._stmt(stmt, state, protected)

    def _stmt(
        self, stmt: ast.stmt, state: dict, protected: frozenset
    ) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if isinstance(stmt, ast.Assign):
            kind = _acquire_of(stmt.value)
            names: tuple = ()
            target = stmt.targets[0] if len(stmt.targets) == 1 else None
            if isinstance(target, ast.Name):
                names = (target.id,)
            elif isinstance(target, ast.Tuple):
                names = tuple(
                    el.id for el in target.elts
                    if isinstance(el, ast.Name)
                )
            if kind is not None and names:
                # reassignment replaces the binding (the retry-alloc
                # idiom); the PREVIOUS resource was None or reported
                for res in state.values():
                    if res.open and set(res.names) & set(names):
                        res.open = False
                self._counter += 1
                state[self._counter] = _Resource(kind, stmt.value, names)
                return
            # a plain assignment whose RHS mentions a resource name
            # transfers ownership (``row.pages = shared + priv``)
            self._apply_escapes(state, self._names_in(stmt.value))
            # rebinding a tracked name to something else drops it
            if isinstance(target, ast.Name):
                for res in state.values():
                    if res.open and target.id in res.names and (
                        len(res.names) == 1
                    ):
                        res.escaped = True  # err quiet: aliased away
            self._implicit_leaks(stmt, state, protected)
            return
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            released = self._release_in_call(call)
            if released:
                self._apply_release(state, released)
                return
            # non-release call consuming the resource = handoff
            names: set[str] = set()
            for a in list(call.args) + [kw.value for kw in call.keywords]:
                names |= self._names_in(a)
            self._apply_escapes(state, names)
            self._implicit_leaks(stmt, state, protected)
            # bare ``x.acquire()`` statement: a non-with lock acquire
            d = dotted(call.func)
            if (
                d is not None
                and d.endswith(".acquire")
                and "lock" in d.lower()
            ):
                self._counter += 1
                state[self._counter] = _Resource(
                    "lock (non-with acquire)",
                    call,
                    (d.rsplit(".", 1)[0],),
                )
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                # ``return sock`` / ``return (fd, path)`` / ``return
                # wrap(priv)`` transfer ownership; ``return sock.recv()``
                # USES the resource without transferring it — only
                # top-level names and call ARGUMENTS escape, receivers
                # do not
                escaped: set[str] = set()
                top = stmt.value
                for el in (
                    top.elts if isinstance(top, (ast.Tuple, ast.List))
                    else [top]
                ):
                    if isinstance(el, ast.Name):
                        escaped.add(el.id)
                for node in ast.walk(stmt.value):
                    if isinstance(node, ast.Call):
                        for a in list(node.args) + [
                            kw.value for kw in node.keywords
                        ]:
                            escaped |= self._names_in(a)
                self._apply_escapes(state, escaped)
            self._leaks(state, "leaks on this return path")
            return
        if isinstance(stmt, ast.Raise):
            for key, res in state.items():
                if res.open and not res.escaped and not (
                    set(res.names) & protected
                ) and key not in self._reported:
                    self._reported.add(key)
                    self.findings.append(
                        (
                            res.node,
                            res.kind,
                            "leaks on the exception path (raise with no "
                            "try/finally release)",
                        )
                    )
                    res.open = False
            return
        if isinstance(stmt, ast.If):
            guard = self._none_guard(stmt.test)
            then_state = self._clone(state)
            else_state = self._clone(state)
            if guard is not None:
                name, absent_on_then = guard
                if absent_on_then:
                    then_state = self._drop_name(then_state, name)
                else:
                    else_state = self._drop_name(else_state, name)
            self._walk(stmt.body, then_state, protected)
            self._walk(stmt.orelse, else_state, protected)
            self._merge_into(state, [then_state, else_state])
            return
        if isinstance(stmt, ast.Try):
            finally_released = frozenset(
                self._release_names(stmt.finalbody)
            )
            inner = protected | finally_released
            self._walk(stmt.body, state, inner)
            branches = [state]
            for handler in stmt.handlers:
                h_state = self._clone(state)
                self._walk(handler.body, h_state, inner)
                branches.append(h_state)
            self._merge_into(state, branches)
            self._walk(stmt.orelse, state, protected)
            self._walk(stmt.finalbody, state, protected)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            body_state = self._clone(state)
            self._walk(stmt.body, body_state, protected)
            self._walk(stmt.orelse, body_state, protected)
            self._merge_into(state, [state, body_state])
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._walk(stmt.body, state, protected)
            return
        # anything else: expressions inside may consume names
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                names: set[str] = set()
                for a in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    names |= self._names_in(a)
                self._apply_escapes(state, names)


def resource_findings(
    graph: ProgramGraph,
    exception_flow: "ExceptionFlow | None" = None,
) -> Iterable[tuple[FunctionNode, ast.AST, str, str]]:
    """GL603 raw findings: ``(fn, node, kind, why)`` per unbalanced
    acquire. With ``exception_flow``, implicit raises out of resolved
    callees are modeled as exits too."""
    for fn in graph.functions.values():
        # cheap pre-filter: only walk bodies that acquire at all
        has_acquire = False
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call) and (
                _acquire_of(node) is not None
                or (
                    (d := dotted(node.func)) is not None
                    and d.endswith(".acquire")
                    and "lock" in d.lower()
                )
            ):
                has_acquire = True
                break
        if not has_acquire:
            continue
        for node, kind, why in _ResourceWalk(fn, exception_flow).run():
            yield fn, node, kind, why


# ── GL604: whole-program untyped-exception escape ────────────────────────

#: builtin exception classes an untyped raise may spell
BUILTIN_ERRORS = {
    "ValueError", "KeyError", "TypeError", "RuntimeError",
    "IndexError", "OverflowError", "ZeroDivisionError",
}

#: builtin hierarchy for catch matching (child -> parents)
_BUILTIN_PARENTS = {
    "KeyError": ("LookupError",),
    "IndexError": ("LookupError",),
    "ZeroDivisionError": ("ArithmeticError",),
    "OverflowError": ("ArithmeticError",),
}

_CATCH_ALL = {"Exception", "BaseException"}


def _handler_names(handler: ast.ExceptHandler) -> set[str] | None:
    """Caught class names; None = bare ``except:`` (catches all)."""
    if handler.type is None:
        return None
    out: set[str] = set()
    types = (
        handler.type.elts
        if isinstance(handler.type, ast.Tuple)
        else [handler.type]
    )
    for t in types:
        d = dotted(t)
        if d is not None:
            out.add(d.split(".")[-1])
    return out


@dataclass
class _Escape:
    exc: str
    node: ast.AST
    rel_path: str
    chain: tuple


class ExceptionFlow:
    """Escape sets per function: which untyped exception classes an
    explicit ``raise`` lets out, with catch coverage computed per raise
    site and per call site from the enclosing ``try`` blocks."""

    def __init__(self, graph: ProgramGraph) -> None:
        self.graph = graph
        #: fn key -> {exc name: _Escape}
        self.escapes: dict[tuple, dict[str, _Escape]] = {}
        self._covers: dict[tuple, dict[tuple, list]] = {}
        self._raises: dict[tuple, list] = {}
        self._prescan()
        self._fixpoint()

    # ── structure scan: catch coverage at every raise/call site ────────

    def _prescan(self) -> None:
        for key, fn in self.graph.functions.items():
            raises: list = []
            covers: dict[tuple, list] = {}

            def visit(stmts, active, fn=fn, raises=raises, covers=covers):
                for stmt in stmts:
                    if isinstance(
                        stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        continue
                    if isinstance(stmt, ast.Raise):
                        exc = self._raised_class(fn, stmt)
                        if exc is not None:
                            raises.append((exc, stmt, list(active)))
                    for node in self._shallow_calls(stmt):
                        covers[
                            (node.lineno, node.col_offset)
                        ] = list(active)
                    if isinstance(stmt, ast.Try):
                        handler_sets = [
                            _handler_names(h) for h in stmt.handlers
                        ]
                        visit(stmt.body, active + [handler_sets])
                        for h in stmt.handlers:
                            visit(h.body, active)
                        visit(stmt.orelse, active)
                        visit(stmt.finalbody, active)
                    else:
                        for child in self._child_blocks(stmt):
                            visit(child, active)

            visit(getattr(fn.node, "body", []), [])
            self._raises[key] = raises
            self._covers[key] = covers

    @staticmethod
    def _child_blocks(stmt: ast.stmt):
        for name in ("body", "orelse", "finalbody"):
            block = getattr(stmt, name, None)
            if isinstance(block, list) and block and isinstance(
                block[0], ast.stmt
            ):
                yield block

    @staticmethod
    def _shallow_calls(stmt: ast.stmt):
        """Calls in ``stmt``'s own expressions — not in nested statement
        blocks (those get their own, deeper, coverage context) and not
        in nested defs."""
        nested: set = set()
        for name in ("body", "orelse", "finalbody", "handlers"):
            block = getattr(stmt, name, None)
            if isinstance(block, list):
                for sub in block:
                    if isinstance(sub, ast.AST):
                        nested.add(sub)
        stack = [
            c
            for c in ast.iter_child_nodes(stmt)
            if c not in nested
        ]
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(node, ast.Call):
                yield node
            stack.extend(ast.iter_child_nodes(node))

    def _raised_class(
        self, fn: FunctionNode, stmt: ast.Raise
    ) -> str | None:
        """The raised class name when it is UNTYPED (a builtin error or
        a parsed class that does not inherit ``PyGridError``)."""
        exc = stmt.exc
        if exc is None:
            return None  # bare re-raise: the original catch governs
        name = None
        if isinstance(exc, ast.Call):
            name = dotted(exc.func)
        else:
            name = dotted(exc)
        if name is None:
            return None
        short = name.split(".")[-1]
        cls_key = self.graph.resolve_class(fn.rel_path, name)
        if cls_key is None and "." in name:
            cls_key = self.graph.resolve_class(fn.rel_path, short)
        if cls_key is not None:
            if self.graph.is_subclass_of(cls_key, "PyGridError"):
                return None
            return short
        if short in BUILTIN_ERRORS:
            return short
        return None  # unresolvable: err quiet, not wrong

    # ── catch matching ─────────────────────────────────────────────────

    def _covered(
        self, exc: str, active: list, rel: str
    ) -> bool:
        """Does any enclosing try's handler set catch ``exc``?"""
        for handler_sets in active:
            for names in handler_sets:
                if names is None:
                    return True  # bare except
                if names & _CATCH_ALL:
                    return True
                if exc in names:
                    return True
                for parent in _BUILTIN_PARENTS.get(exc, ()):
                    if parent in names:
                        return True
                cls_key = self.graph.resolve_class(rel, exc)
                if cls_key is not None:
                    for base in self.graph.mro(cls_key):
                        if base[1] in names:
                            return True
        return False

    # ── escape propagation ─────────────────────────────────────────────

    def _fixpoint(self) -> None:
        for key in self.graph.functions:
            self.escapes[key] = {}
        for _ in range(_MAX_PASSES * 2):
            changed = False
            for key, fn in self.graph.functions.items():
                out = self.escapes[key]
                for exc, node, active in self._raises[key]:
                    if exc in out:
                        continue
                    if not self._covered(exc, active, fn.rel_path):
                        out[exc] = _Escape(
                            exc,
                            node,
                            fn.rel_path,
                            (
                                f"raise {exc} in {fn.pretty} at "
                                f"{fn.rel_path}:{node.lineno}",
                            ),
                        )
                        changed = True
                for call in fn.calls:
                    active = self._covers.get(key, {}).get(
                        (call.node.lineno, call.node.col_offset)
                    )
                    if active is None:
                        continue
                    for target in call.targets:
                        callee = self.graph.functions.get(target)
                        if callee is None:
                            continue
                        if callee.is_async and not fn.is_async:
                            # calling an async def from sync code only
                            # schedules it — its raises surface at the
                            # await, not on this stack
                            continue
                        for exc, esc in self.escapes.get(
                            target, {}
                        ).items():
                            if exc in out:
                                continue
                            if self._covered(exc, active, fn.rel_path):
                                continue
                            step = (
                                f"called from {fn.pretty} at "
                                f"{fn.rel_path}:{call.node.lineno}"
                            )
                            out[exc] = _Escape(
                                exc,
                                esc.node,
                                esc.rel_path,
                                esc.chain + (step,),
                            )
                            changed = True
            if not changed:
                break


def boundary_entry_points(graph: ProgramGraph) -> dict[tuple, str]:
    """Protocol-boundary entry functions: HTTP handlers registered via
    ``r.add_*`` in the route modules, and WS handlers dispatched
    through a ``ROUTES`` table. Returns ``{fn key: description}``."""
    import fnmatch

    patterns = (
        "*/node/routes.py", "*/network/routes.py", "*/node/events.py",
        "*/node/ws.py", "*/network/ws.py", "*/users/events.py",
        "*/users/routes.py",
    )
    add_methods = {
        "add_get", "add_post", "add_put", "add_delete", "add_patch",
        "add_head", "add_route",
    }
    out: dict[tuple, str] = {}
    for rel, syms in graph.modules.items():
        if not any(fnmatch.fnmatch(rel, p) for p in patterns):
            continue
        for node in ast.walk(syms.tree):
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ) and node.func.attr in add_methods:
                idx = 1 if node.func.attr == "add_route" else 0
                args = node.args[idx + 1:idx + 2]
                for arg in args:
                    # wrapped registrations — ``add_post("/x",
                    # _ws_twin(EVENT))`` — enter through the factory
                    if isinstance(arg, ast.Call):
                        arg = arg.func
                    d = dotted(arg)
                    if d is None:
                        continue
                    hits = graph.resolve_call(rel, None, d, None)
                    for hit in hits:
                        out.setdefault(hit, f"HTTP route handler ({rel})")
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                # every dispatch table in a handler module is an entry
                # surface: ROUTES itself AND the *_HANDLERS dicts that
                # get **-merged into it (the merge spells key=None in
                # the AST, so the source dict must be collected where
                # it is defined — users/events.py's USER_HANDLERS)
                named_routes = any(
                    isinstance(t, ast.Name)
                    and (t.id == "ROUTES" or "HANDLERS" in t.id)
                    for t in targets
                )
                if named_routes and isinstance(node.value, ast.Dict):
                    for v in node.value.values:
                        # factory-built handlers (``_user_op(lambda…)``)
                        # dispatch through a closure static analysis
                        # cannot index — the FACTORY body is the
                        # reachable raising surface, so it enters
                        if isinstance(v, ast.Call):
                            v = v.func
                        d = dotted(v)
                        if d is None:
                            continue
                        hits = graph.resolve_call(rel, None, d, None)
                        for hit in hits:
                            out.setdefault(
                                hit, f"WS event handler ({rel})"
                            )
    # explicit annotations: any module (not just the pattern-listed
    # route modules) may declare a module-level GRIDLINT_ENTRY_POINTS
    # tuple/list of function names — protocol boundaries the heuristics
    # can't see, like the sub-aggregator's raw-WS server. Names are
    # either qualnames in the same module ("Cls.method", "fn") or
    # call-style dotted names resolved through the graph.
    for rel, syms in graph.modules.items():
        for stmt in syms.tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, ast.AnnAssign):
                targets = [stmt.target]
            if not any(
                isinstance(t, ast.Name) and t.id == "GRIDLINT_ENTRY_POINTS"
                for t in targets
            ):
                continue
            value = stmt.value
            if not isinstance(value, (ast.Tuple, ast.List)):
                continue
            for elt in value.elts:
                if not (
                    isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)
                ):
                    continue
                name = elt.value
                hits = []
                if (rel, name) in graph.functions:
                    hits = [(rel, name)]
                else:
                    hits = graph.resolve_call(rel, None, name, None)
                for hit in hits:
                    out.setdefault(hit, f"annotated entry point ({rel})")
    return out
