"""``python -m pygrid_tpu.analysis`` — the gridlint CLI."""

import sys

from pygrid_tpu.analysis.cli import main

sys.exit(main())
