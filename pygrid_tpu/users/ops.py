"""User/role/group operations with role-boolean permission gates.

Parity surface: reference ``apps/node/src/app/main/users/{user_ops,role_ops,
group_ops}.py`` — the same gate per operation (reads gated on
``can_triage_requests``; user mutations on ``can_create_users`` unless
self-editing; role mutations on ``can_edit_roles``; group mutations on
``can_create_groups``), first-user-auto-Owner signup
(``user_ops.py:69-81``), Owner-protection rules in ``change_user_role``
(user id 1 immutable, only Owners mint Owners), and HS256 login tokens
(``user_ops.py:120-135``). Passwords: pbkdf2-HMAC-SHA256 with per-user salt
(the image has no bcrypt; same salt+hash storage shape).
"""

from __future__ import annotations

import hmac
import secrets

from pygrid_tpu.federated.auth import jwt_encode, jwt_verify
from pygrid_tpu.utils.passwords import hash_password, pbkdf2
from pygrid_tpu.storage.warehouse import Database, Warehouse
from pygrid_tpu.users.schemas import Group, Role, User, UserGroup
from pygrid_tpu.utils.exceptions import (
    AuthorizationError,
    GroupNotFoundError,
    InvalidCredentialsError,
    MissingRequestKeyError,
    RoleNotFoundError,
    UserNotFoundError,
)

#: the four seeded roles (reference app/__init__.py:79-129)
_SEED_ROLES = [
    dict(name="User"),
    dict(name="Compliance Officer", can_triage_requests=True),
    dict(
        name="Administrator",
        can_triage_requests=True,
        can_edit_settings=True,
        can_create_users=True,
        can_create_groups=True,
        can_upload_data=True,
    ),
    dict(
        name="Owner",
        can_triage_requests=True,
        can_edit_settings=True,
        can_create_users=True,
        can_create_groups=True,
        can_edit_roles=True,
        can_manage_infrastructure=True,
        can_upload_data=True,
        can_manage_nodes=True,
    ),
]


def salt_and_hash_password(password: str, salt: str | None = None):
    """Hex (salt, digest) over the shared pbkdf2 helper — the User schema
    stores both as TEXT columns (reference user.py salt/hashed_password)."""
    if salt is None:
        salt_bytes, digest = hash_password(password)
        return salt_bytes.hex(), digest.hex()
    return salt, pbkdf2(password, bytes.fromhex(salt)).hex()


def seed_roles(db: Database) -> None:
    roles = Warehouse(Role, db)
    if roles.count() == 0:
        for spec in _SEED_ROLES:
            roles.register(**spec)


class UserManager:
    """All RBAC operations for one app (node or network)."""

    def __init__(self, db: Database, secret_key: str | None = None) -> None:
        self.users = Warehouse(User, db)
        self.roles = Warehouse(Role, db)
        self.groups = Warehouse(Group, db)
        self.usergroups = Warehouse(UserGroup, db)
        self.secret_key = secret_key or secrets.token_hex(16)
        seed_roles(db)

    # ── internals ─────────────────────────────────────────────────────────

    def role_of(self, user: User) -> Role:
        role = self.roles.first(id=user.role)
        if role is None:
            raise RoleNotFoundError()
        return role

    def _require(self, user: User, permission: str) -> Role:
        role = self.role_of(user)
        if not getattr(role, permission):
            raise AuthorizationError()
        return role

    def identify_user(self, private_key: str | None) -> tuple[User, Role]:
        if private_key is None:
            raise MissingRequestKeyError()
        user = self.users.first(private_key=private_key)
        if user is None:
            raise UserNotFoundError()
        return user, self.role_of(user)

    # ── signup / login / token resolution ────────────────────────────────

    def signup(
        self,
        email: str,
        password: str,
        role: int | None = None,
        private_key: str | None = None,
    ) -> User:
        """First user becomes Owner; an authenticated can_create_users caller
        may pick the new user's role; everyone else lands on 'User'
        (reference user_ops.py:54-107)."""
        creator = creator_role = None
        if private_key is not None:
            creator, creator_role = self.identify_user(private_key)

        new_key = secrets.token_hex(32)
        salt, hashed = salt_and_hash_password(password)

        if self.users.count() == 0:
            assigned = self._role_id_by_name("Owner")
        elif (
            role is not None
            and creator_role is not None
            and creator_role.can_create_users
        ):
            if self.roles.first(id=role) is None:
                raise RoleNotFoundError()
            assigned = int(role)
        else:
            assigned = self._role_id_by_name("User")

        return self.users.register(
            email=email,
            hashed_password=hashed,
            salt=salt,
            private_key=new_key,
            role=assigned,
        )

    def _role_id_by_name(self, name: str) -> int:
        role = self.roles.first(name=name)
        if role is None:
            raise RoleNotFoundError()
        return role.id

    def login(
        self, email: str, password: str, private_key: str | None = None
    ) -> str:
        filters = {"email": email}
        if private_key is not None:
            filters["private_key"] = private_key
        user = self.users.first(**filters)
        if user is None:
            raise InvalidCredentialsError()
        _, hashed = salt_and_hash_password(password, user.salt)
        if not hmac.compare_digest(hashed, user.hashed_password):
            raise InvalidCredentialsError()
        return jwt_encode({"id": user.id}, secret=self.secret_key)

    def resolve_token(self, token: str | None) -> User:
        """JWT → User (reference auth.py token_required_factory:22-52)."""
        if token is None:
            raise MissingRequestKeyError()
        try:
            data = jwt_verify(token, secret=self.secret_key)
        except Exception as err:
            raise InvalidCredentialsError() from err
        user = self.users.first(id=data.get("id"))
        if user is None:
            raise UserNotFoundError()
        return user

    # ── user CRUD (gated) ─────────────────────────────────────────────────

    def get_all_users(self, current: User) -> list[User]:
        self._require(current, "can_triage_requests")
        return self.users.query()

    def get_user(self, current: User, user_id: int) -> User:
        self._require(current, "can_triage_requests")
        user = self.users.first(id=user_id)
        if user is None:
            raise UserNotFoundError()
        return user

    def search_users(self, current: User, **filters) -> list[User]:
        self._require(current, "can_triage_requests")
        return self.users.query(**filters)

    def _editable(self, current: User, user_id: int) -> User:
        if user_id != current.id:
            self._require(current, "can_create_users")
        user = self.users.first(id=user_id)
        if user is None:
            raise UserNotFoundError()
        return user

    def change_email(self, current: User, user_id: int, email: str) -> User:
        self._editable(current, user_id)
        self.users.modify({"id": user_id}, {"email": email})
        return self.users.first(id=user_id)

    def change_password(
        self, current: User, user_id: int, password: str
    ) -> User:
        self._editable(current, user_id)
        salt, hashed = salt_and_hash_password(password)
        self.users.modify(
            {"id": user_id}, {"salt": salt, "hashed_password": hashed}
        )
        return self.users.first(id=user_id)

    def change_role(self, current: User, user_id: int, role: int) -> User:
        if user_id == 1:  # the Owner account's role is immutable
            raise AuthorizationError()
        self._editable(current, user_id)
        owner_role_id = self._role_id_by_name("Owner")
        current_role = self.role_of(current)
        # only Owners may mint Owners (reference user_ops.py:184-186)
        if int(role) == owner_role_id and current_role.name != "Owner":
            raise AuthorizationError()
        if self.roles.first(id=role) is None:
            raise RoleNotFoundError()
        self.users.modify({"id": user_id}, {"role": int(role)})
        return self.users.first(id=user_id)

    def change_groups(
        self, current: User, user_id: int, groups: list[int]
    ) -> None:
        self._editable(current, user_id)
        for g in groups:
            if self.groups.first(id=g) is None:
                raise GroupNotFoundError()
        self.usergroups.delete(user=user_id)
        for g in groups:
            self.usergroups.register(user=user_id, group=int(g))

    def user_groups(self, user_id: int) -> list[Group]:
        links = self.usergroups.query(user=user_id)
        return [self.groups.first(id=link.group) for link in links]

    def delete_user(self, current: User, user_id: int) -> None:
        if user_id != current.id:
            self._require(current, "can_create_users")
        if self.users.first(id=user_id) is None:
            raise UserNotFoundError()
        self.usergroups.delete(user=user_id)
        self.users.delete(id=user_id)

    # ── role CRUD (gated) ─────────────────────────────────────────────────

    def create_role(self, current: User, **fields) -> Role:
        self._require(current, "can_edit_roles")
        return self.roles.register(**fields)

    def get_role(self, current: User, role_id: int) -> Role:
        self._require(current, "can_triage_requests")
        role = self.roles.first(id=role_id)
        if role is None:
            raise RoleNotFoundError()
        return role

    def get_all_roles(self, current: User) -> list[Role]:
        self._require(current, "can_triage_requests")
        return self.roles.query()

    def put_role(self, current: User, role_id: int, **fields) -> Role:
        self._require(current, "can_edit_roles")
        if self.roles.first(id=role_id) is None:
            raise RoleNotFoundError()
        self.roles.modify({"id": role_id}, fields)
        return self.roles.first(id=role_id)

    def delete_role(self, current: User, role_id: int) -> None:
        self._require(current, "can_edit_roles")
        if self.roles.first(id=role_id) is None:
            raise RoleNotFoundError()
        self.roles.delete(id=role_id)

    # ── group CRUD (gated) ────────────────────────────────────────────────

    def create_group(self, current: User, name: str) -> Group:
        self._require(current, "can_create_groups")
        return self.groups.register(name=name)

    def get_group(self, current: User, group_id: int) -> Group:
        self._require(current, "can_triage_requests")
        group = self.groups.first(id=group_id)
        if group is None:
            raise GroupNotFoundError()
        return group

    def get_all_groups(self, current: User) -> list[Group]:
        self._require(current, "can_triage_requests")
        return self.groups.query()

    def put_group(self, current: User, group_id: int, **fields) -> Group:
        self._require(current, "can_create_groups")
        if self.groups.first(id=group_id) is None:
            raise GroupNotFoundError()
        self.groups.modify({"id": group_id}, fields)
        return self.groups.first(id=group_id)

    def delete_group(self, current: User, group_id: int) -> None:
        self._require(current, "can_create_groups")
        if self.groups.first(id=group_id) is None:
            raise GroupNotFoundError()
        self.usergroups.delete(group=group_id)
        self.groups.delete(id=group_id)
