"""User/role/group WS event handlers — shared by Node and Network.

Parity surface: the reference implements these twice with the same pattern
(``apps/node/src/app/main/events/{user,role,group}_related.py`` and
``apps/network/src/app/events/*``); here one table serves both apps. A
handler takes any context exposing ``.users`` (a
:class:`pygrid_tpu.users.UserManager`) plus the raw message dict."""

from __future__ import annotations

from dataclasses import asdict
from typing import Any, Callable

from pygrid_tpu.utils import exceptions as E
from pygrid_tpu.utils.codes import (
    CYCLE,
    GROUP_EVENTS,
    MSG_FIELD,
    ROLE_EVENTS,
    USER_EVENTS,
)

SUCCESS = "success"
ERROR = "error"


def serializable(obj: Any) -> Any:
    """Dataclass → dict with secret fields stripped."""
    if hasattr(obj, "__dataclass_fields__"):
        d = asdict(obj)
        d.pop("hashed_password", None)
        d.pop("salt", None)
        d.pop("private_key", None)
        return d
    return obj


def _user_op(fn: Callable) -> Callable:
    """Wrap a UserManager call: resolve the token, format the response."""

    def wrapper(ctx: Any, message: dict, conn: Any = None) -> dict:
        data = message.get(MSG_FIELD.DATA) or message
        try:
            current = ctx.users.resolve_token(data.get("token"))
            result = fn(ctx, current, data)
            if isinstance(result, list):
                result = [serializable(r) for r in result]
            else:
                result = serializable(result)
            return {CYCLE.STATUS: SUCCESS, MSG_FIELD.DATA: result}
        except E.PyGridError as err:
            return {ERROR: str(err)}

    return wrapper


def signup_user(ctx: Any, message: dict, conn: Any = None) -> dict:
    data = message.get(MSG_FIELD.DATA) or message
    try:
        user = ctx.users.signup(
            data.get("email"),
            data.get("password"),
            role=data.get("role"),
            private_key=data.get("private-key"),
        )
        return {CYCLE.STATUS: SUCCESS, "user": serializable(user)}
    except E.PyGridError as err:
        return {ERROR: str(err)}


def login_user(ctx: Any, message: dict, conn: Any = None) -> dict:
    data = message.get(MSG_FIELD.DATA) or message
    try:
        token = ctx.users.login(
            data.get("email"),
            data.get("password"),
            private_key=data.get("private-key"),
        )
        return {CYCLE.STATUS: SUCCESS, "token": token}
    except E.PyGridError as err:
        return {ERROR: str(err)}


USER_HANDLERS: dict[str, Callable] = {
    USER_EVENTS.SIGNUP_USER: signup_user,
    USER_EVENTS.LOGIN_USER: login_user,
    USER_EVENTS.GET_ALL_USERS: _user_op(
        lambda ctx, cur, d: ctx.users.get_all_users(cur)
    ),
    USER_EVENTS.GET_SPECIFIC_USER: _user_op(
        lambda ctx, cur, d: ctx.users.get_user(cur, int(d["id"]))
    ),
    USER_EVENTS.SEARCH_USERS: _user_op(
        lambda ctx, cur, d: ctx.users.search_users(
            cur, **{k: v for k, v in d.items() if k in ("email", "role")}
        )
    ),
    USER_EVENTS.PUT_EMAIL: _user_op(
        lambda ctx, cur, d: ctx.users.change_email(cur, int(d["id"]), d["email"])
    ),
    USER_EVENTS.PUT_PASSWORD: _user_op(
        lambda ctx, cur, d: ctx.users.change_password(
            cur, int(d["id"]), d["password"]
        )
    ),
    USER_EVENTS.PUT_ROLE: _user_op(
        lambda ctx, cur, d: ctx.users.change_role(cur, int(d["id"]), d["role"])
    ),
    USER_EVENTS.PUT_GROUPS: _user_op(
        lambda ctx, cur, d: ctx.users.change_groups(
            cur, int(d["id"]), d["groups"]
        )
    ),
    USER_EVENTS.DELETE_USER: _user_op(
        lambda ctx, cur, d: ctx.users.delete_user(cur, int(d["id"]))
    ),
    ROLE_EVENTS.CREATE_ROLE: _user_op(
        lambda ctx, cur, d: ctx.users.create_role(
            cur, **{k: v for k, v in d.items() if k != "token"}
        )
    ),
    ROLE_EVENTS.GET_ROLE: _user_op(
        lambda ctx, cur, d: ctx.users.get_role(cur, int(d["id"]))
    ),
    ROLE_EVENTS.GET_ALL_ROLES: _user_op(
        lambda ctx, cur, d: ctx.users.get_all_roles(cur)
    ),
    ROLE_EVENTS.PUT_ROLE: _user_op(
        lambda ctx, cur, d: ctx.users.put_role(
            cur, int(d["id"]),
            **{k: v for k, v in d.items() if k not in ("token", "id")},
        )
    ),
    ROLE_EVENTS.DELETE_ROLE: _user_op(
        lambda ctx, cur, d: ctx.users.delete_role(cur, int(d["id"]))
    ),
    GROUP_EVENTS.CREATE_GROUP: _user_op(
        lambda ctx, cur, d: ctx.users.create_group(cur, d["name"])
    ),
    GROUP_EVENTS.GET_GROUP: _user_op(
        lambda ctx, cur, d: ctx.users.get_group(cur, int(d["id"]))
    ),
    GROUP_EVENTS.GET_ALL_GROUPS: _user_op(
        lambda ctx, cur, d: ctx.users.get_all_groups(cur)
    ),
    GROUP_EVENTS.PUT_GROUP: _user_op(
        lambda ctx, cur, d: ctx.users.put_group(
            cur, int(d["id"]),
            **{k: v for k, v in d.items() if k not in ("token", "id")},
        )
    ),
    GROUP_EVENTS.DELETE_GROUP: _user_op(
        lambda ctx, cur, d: ctx.users.delete_group(cur, int(d["id"]))
    ),
}


def http_twin(event_type: str, ctx_key: str):
    """HTTP twin of a user/role/group WS event, shared by Node
    (``app["node"]``) and Network (``app["network"]``).

    Path parameters take precedence over JSON body keys (the URL names the
    resource; a body ``id`` must not silently retarget it), and malformed
    input maps to 400, not 500."""
    import json

    from aiohttp import web

    async def handler(request):
        ctx = request.app[ctx_key]
        try:
            body = (
                json.loads(await request.text())
                if request.can_read_body
                else {}
            )
            if not isinstance(body, dict):
                # typed, like every protocol-boundary defect: a bare
                # ValueError here would be indistinguishable from an
                # internal bug to middleware and tests (gridlint GL604)
                raise E.PyGridError("JSON object body required")
        except (
            json.JSONDecodeError,
            UnicodeDecodeError,  # request.text() on undecodable bytes
            E.PyGridError,
        ) as err:
            return web.json_response({ERROR: str(err)}, status=400)
        token = request.headers.get("token")
        if token and "token" not in body:
            body["token"] = token
        body.update(request.match_info)
        try:
            response = USER_HANDLERS[event_type](ctx, {MSG_FIELD.DATA: body})
        except (ValueError, KeyError, TypeError, AttributeError) as err:
            return web.json_response({ERROR: str(err)}, status=400)
        status = 200 if ERROR not in response else 400
        return web.json_response(response, status=status)

    return handler
