"""RBAC schemas.

Parity surface: reference ``apps/node/src/app/main/database/{role,user,group,
usergroup}.py`` — same tables, same columns (Role's seven permission
booleans; User's email/hashed_password/salt/private_key/role; Group;
UserGroup join table). The Network app adds ``can_manage_nodes`` to its Role
(reference ``apps/network/src/app/database/role.py``) — carried here as an
optional eighth boolean so one schema serves both apps.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class Role:
    id: int | None = None
    name: str = ""
    can_triage_requests: bool = False
    can_edit_settings: bool = False
    can_create_users: bool = False
    can_create_groups: bool = False
    can_edit_roles: bool = False
    can_manage_infrastructure: bool = False
    can_upload_data: bool = False
    can_manage_nodes: bool = False  # network-app extension


@dataclass
class User:
    id: int | None = None
    email: str = ""
    hashed_password: str = ""
    salt: str = ""
    private_key: str = ""
    role: int = 0


@dataclass
class Group:
    id: int | None = None
    name: str = ""


@dataclass
class UserGroup:
    id: int | None = None
    user: int = 0
    group: int = 0
