"""RBAC / user management, shared by the Node and Network apps.

Parity surface: reference ``apps/node/src/app/main/{users,database,auth}.py``
(~1200 LoC) and the Network twin (``apps/network/src/app/users/``): bcrypt-
salted signup/login (pbkdf2 here — no bcrypt in the image), first user
auto-Owner, JWT HS256 session tokens, role-boolean permission gates, group
membership, and a transport-agnostic ``token_required`` resolver used by
both the HTTP routes and their WS event twins.
"""

from pygrid_tpu.users.ops import UserManager, seed_roles
from pygrid_tpu.users.schemas import Group, Role, User, UserGroup

__all__ = ["UserManager", "seed_roles", "Group", "Role", "User", "UserGroup"]
