"""Network HTTP routes.

Parity surface: reference ``apps/network/src/app/routes/network.py`` —
/join (:22), /connected-nodes (:55), /delete-node (:67),
/choose-encrypted-model-host (:98, n_replica × SMPC_HOST_CHUNK sampling),
/choose-model-host (:134), /search-encrypted-model (:157, fan-out),
/search-model (:201), /search-available-models (:229),
/search-available-tags (:247), /search (:266) — plus /models and /datasets
aggregates (``routes/models.py``, ``routes/dataset.py``) and the users CRUD
twin. Fan-outs run concurrently (asyncio.gather) instead of the reference's
sequential requests loop; per-node connection errors are swallowed the same
way (reference network.py:173-175).
"""

from __future__ import annotations

import asyncio
import json
import logging
import random
from typing import Any

import aiohttp
from aiohttp import web

from pygrid_tpu.network import SMPC_HOST_CHUNK, NetworkContext

logger = logging.getLogger(__name__)

INVALID_JSON_FORMAT_MESSAGE = "Invalid JSON format."


def _ctx(request: web.Request) -> NetworkContext:
    return request.app["network"]


async def _fanout(
    nodes: dict[str, str],
    path: str,
    method: str = "get",
    body: dict | None = None,
) -> dict[str, Any]:
    """Concurrently hit `path` on every node; unreachable nodes drop out."""
    timeout = aiohttp.ClientTimeout(total=10)

    async def one(node_id: str, address: str):
        try:
            async with aiohttp.ClientSession(timeout=timeout) as session:
                if method == "get":
                    async with session.get(address + path) as resp:
                        return node_id, await resp.json()
                async with session.post(address + path, json=body) as resp:
                    return node_id, await resp.json()
        except Exception:  # noqa: BLE001 — reference swallows ConnectionError
            return node_id, None

    results = await asyncio.gather(
        *(one(nid, addr) for nid, addr in nodes.items())
    )
    return {nid: payload for nid, payload in results if payload is not None}


# ── registry ────────────────────────────────────────────────────────────────


async def join(request: web.Request) -> web.Response:
    try:
        data = json.loads(await request.text())
        ok = _ctx(request).manager.register_new_node(
            data["node-id"], data["node-address"]
        )
        if ok:
            _ctx(request).proxy(data["node-id"], data["node-address"])
            return web.json_response({"message": "Successfully Connected!"})
        return web.json_response(
            {"message": "This ID has already been registered"}, status=409
        )
    except (ValueError, KeyError):
        return web.json_response(
            {"message": INVALID_JSON_FORMAT_MESSAGE}, status=400
        )


async def metrics(request: web.Request) -> web.Response:
    """Prometheus text exposition for the Network (nodes + proxy states) —
    the node app serves its own /metrics; the reference has neither
    (SURVEY §5.5)."""
    ctx = _ctx(request)
    from pygrid_tpu import telemetry
    from pygrid_tpu.utils.metrics import Exposition

    exp = Exposition()
    nodes = ctx.manager.connected_nodes()
    exp.gauge("grid_nodes_total", len(nodes),
              "nodes registered with the network")
    by_status: dict[str, int] = {}
    for proxy in ctx.proxies.values():
        by_status[proxy.status] = by_status.get(proxy.status, 0) + 1
    for status in ("online", "degraded", "busy", "offline"):
        exp.gauge("grid_nodes", by_status.get(status, 0),
                  "nodes by monitor status", {"status": status})
    exp.gauge("grid_subaggregators_total", len(ctx.aggregation.live()),
              "live sub-aggregators registered for placement")
    # the telemetry bus: request latency by route, heartbeat RTT by
    # transport, monitor poll outcomes, event counters
    telemetry.export(exp)
    # heartbeat SLO compliance/burn gauges (telemetry/slo.py)
    ctx.slo.export(exp)
    return web.Response(
        text=exp.render(), content_type="text/plain", charset="utf-8"
    )


async def connected_nodes(request: web.Request) -> web.Response:
    nodes = _ctx(request).manager.connected_nodes()
    return web.json_response({"grid-nodes": list(nodes.keys())})


async def delete_node(request: web.Request) -> web.Response:
    try:
        data = json.loads(await request.text())
        ok = _ctx(request).manager.delete_node(
            data["node-id"], data["node-address"]
        )
        if ok:
            _ctx(request).proxies.pop(data["node-id"], None)
            return web.json_response({"message": "Successfully Deleted!"})
        return web.json_response(
            {"message": "This ID was not found in connected nodes"}, status=409
        )
    except (ValueError, KeyError):
        return web.json_response(
            {"message": INVALID_JSON_FORMAT_MESSAGE}, status=400
        )


# ── host selection ──────────────────────────────────────────────────────────


async def choose_encrypted_model_host(request: web.Request) -> web.Response:
    """Sample n_replica × SMPC_HOST_CHUNK nodes to hold shares
    (reference network.py:98-131)."""
    ctx = _ctx(request)
    nodes = ctx.manager.connected_nodes()
    try:
        hosts = random.sample(
            list(nodes.keys()), ctx.n_replica * SMPC_HOST_CHUNK
        )
    except ValueError:  # not enough nodes
        return web.json_response([], status=400)
    return web.json_response([(h, nodes[h]) for h in hosts])


async def _get_model_hosting_nodes(
    ctx: NetworkContext, model_id: str
) -> list:
    nodes = ctx.manager.connected_nodes()
    results = await _fanout(nodes, "/data-centric/models/")
    return [
        (nid, nodes[nid])
        for nid, payload in results.items()
        if model_id in (payload.get("models") or [])
    ]


async def choose_model_host(request: web.Request) -> web.Response:
    ctx = _ctx(request)
    nodes = ctx.manager.connected_nodes()
    model_id = request.query.get("model_id")
    hosts_info = None
    if model_id:
        hosts_info = await _get_model_hosting_nodes(ctx, model_id)
    if not hosts_info:
        try:
            hosts = random.sample(list(nodes.keys()), ctx.n_replica or 1)
        except ValueError:
            return web.json_response([], status=400)
        hosts_info = [(h, nodes[h]) for h in hosts]
    return web.json_response(hosts_info)


# ── search fan-outs ─────────────────────────────────────────────────────────


async def search_encrypted_model(request: web.Request) -> web.Response:
    """(reference network.py:157-198) → {node: {address, nodes: {workers,
    crypto_provider}}} for every node hosting shares of the model."""
    ctx = _ctx(request)
    try:
        body = json.loads(await request.text())
    except ValueError:
        return web.json_response(
            {"message": INVALID_JSON_FORMAT_MESSAGE}, status=400
        )
    nodes = ctx.manager.connected_nodes()
    results = await _fanout(
        nodes, "/data-centric/search-encrypted-models", "post", body
    )
    match_nodes = {
        nid: {
            "address": nodes[nid],
            "nodes": payload,
            # share-holders/providers that are themselves grid nodes get
            # their addresses resolved here, so a client can dial them
            # without out-of-band knowledge (the reference assumes the
            # client already knows the grid map; this is strictly more)
            "worker_addresses": {
                wid: nodes[wid]
                for wid in (
                    payload.get("workers", [])
                    + payload.get("crypto_provider", [])
                )
                if wid in nodes
            },
        }
        for nid, payload in results.items()
        if {"workers", "crypto_provider"} <= set(payload.keys())
    }
    return web.json_response({"match-nodes": match_nodes})


async def search_model(request: web.Request) -> web.Response:
    try:
        body = json.loads(await request.text())
        match = await _get_model_hosting_nodes(_ctx(request), body["model_id"])
        return web.json_response({"match-nodes": match})
    except (ValueError, KeyError):
        return web.json_response(
            {"message": INVALID_JSON_FORMAT_MESSAGE}, status=400
        )


async def search_available_models(request: web.Request) -> web.Response:
    nodes = _ctx(request).manager.connected_nodes()
    results = await _fanout(nodes, "/data-centric/models/")
    models: set[str] = set()
    for payload in results.values():
        models.update(payload.get("models") or [])
    return web.json_response({"models": sorted(models)})


async def search_available_tags(request: web.Request) -> web.Response:
    nodes = _ctx(request).manager.connected_nodes()
    results = await _fanout(nodes, "/data-centric/dataset-tags")
    tags: set[str] = set()
    for payload in results.values():
        if isinstance(payload, list):
            tags.update(payload)
    return web.json_response({"tags": sorted(tags)})


async def search(request: web.Request) -> web.Response:
    """(reference network.py:266-306) dataset tag search → [(id, address)]."""
    ctx = _ctx(request)
    try:
        body = json.loads(await request.text())
        query = body["query"]
    except (ValueError, KeyError):
        return web.json_response(
            {"message": INVALID_JSON_FORMAT_MESSAGE}, status=400
        )
    nodes = ctx.manager.connected_nodes()
    results = await _fanout(
        nodes, "/data-centric/search", "post", {"query": query}
    )
    matches = [
        (nid, nodes[nid])
        for nid, payload in results.items()
        if payload.get("content")
    ]
    return web.json_response({"match-nodes": matches})


# ── hierarchical aggregation (docs/AGGREGATION.md) ──────────────────────────


async def aggregation_register(request: web.Request) -> web.Response:
    """A sub-aggregator registers (and re-registers as its heartbeat):
    ``{subagg-id, subagg-address, node-address}`` — the node (or parent
    sub-aggregator) address is the upstream its partials flow to."""
    try:
        data = json.loads(await request.text())
        entry = _ctx(request).aggregation.register(
            data["subagg-id"], data["subagg-address"], data["node-address"]
        )
    except (ValueError, KeyError, TypeError):
        return web.json_response(
            {"message": INVALID_JSON_FORMAT_MESSAGE}, status=400
        )
    return web.json_response(
        {"message": "registered", "ttl_s": _ctx(request).aggregation.ttl_s,
         "subagg-id": entry.subagg_id}
    )


async def aggregation_unregister(request: web.Request) -> web.Response:
    try:
        data = json.loads(await request.text())
        ok = _ctx(request).aggregation.remove(data["subagg-id"])
    except (ValueError, KeyError):
        return web.json_response(
            {"message": INVALID_JSON_FORMAT_MESSAGE}, status=400
        )
    return web.json_response(
        {"message": "removed" if ok else "unknown sub-aggregator"},
        status=200 if ok else 404,
    )


async def aggregation_placement(request: web.Request) -> web.Response:
    """Worker→sub-aggregator routing: ``?node-address=…&worker-id=…`` →
    ``{report-to: address | null}``. Null means report direct to the
    node — the fallback whenever no live sub-aggregator serves it."""
    node_address = request.query.get("node-address")
    worker_id = request.query.get("worker-id")
    if not node_address or not worker_id:
        return web.json_response(
            {"message": "node-address and worker-id are required"},
            status=400,
        )
    entry = _ctx(request).aggregation.place(node_address, worker_id)
    return web.json_response(
        {
            "report-to": entry.address if entry else None,
            "subagg-id": entry.subagg_id if entry else None,
        }
    )


async def aggregation_tree(request: web.Request) -> web.Response:
    """The live tree topology + knobs (fanout/depth/ttl) for operators
    and the dashboard."""
    return web.json_response(_ctx(request).aggregation.tree())


# ── monitor aggregates (reference routes/models.py, routes/dataset.py) ──────


async def models(request: web.Request) -> web.Response:
    ctx = _ctx(request)
    return web.json_response(
        {"models": [p.hosted_models for p in ctx.proxies.values()]}
    )


async def datasets(request: web.Request) -> web.Response:
    ctx = _ctx(request)
    return web.json_response(
        {"datasets": [p.hosted_datasets for p in ctx.proxies.values()]}
    )


async def telemetry_slo(request: web.Request) -> web.Response:
    """The network's burn-rate SLO view (heartbeat RTT, per-node burn
    under ``by_node``) — twin of the node's route, same payload shape."""
    return web.json_response({"slo": _ctx(request).slo.evaluate()})


async def healthz(request: web.Request) -> web.Response:
    """Shallow 200 for LB probes; ``?deep=1`` answers 503 when the
    heartbeat SLO is in breach or a majority of nodes are unreachable."""
    if request.query.get("deep") not in ("1", "true", "yes"):
        return web.json_response({"status": "ok"})
    ctx = _ctx(request)
    rows = ctx.slo.evaluate()
    breaches = [r["name"] for r in rows if r["status"] == "breach"]
    proxies = list(ctx.proxies.values())
    offline = [p.id for p in proxies if p.status == "offline"]
    unhealthy = bool(breaches) or (
        len(proxies) > 0 and len(offline) > len(proxies) / 2
    )
    return web.json_response(
        {
            "status": "breach" if unhealthy else "ok",
            "breaches": breaches,
            "nodes_offline": offline,
            "nodes_total": len(proxies),
            "slo": rows,
        },
        status=503 if unhealthy else 200,
    )


async def nodes_status(request: web.Request) -> web.Response:
    ctx = _ctx(request)
    return web.json_response(
        {
            nid: {
                "address": p.address,
                "status": p.status,
                "ping_ms": p.ping,
                "models": p.hosted_models,
                "datasets": p.hosted_datasets,
                "location": p.location,
            }
            for nid, p in ctx.proxies.items()
        }
    )


def register(app: web.Application) -> None:
    from pygrid_tpu.users.events import http_twin
    from pygrid_tpu.utils.codes import ROLE_EVENTS, USER_EVENTS

    def _rbac_twin(event_type):
        # the shared twin: path params win over body keys, malformed
        # input maps to 400 (see users/events.py http_twin)
        return http_twin(event_type, "network")

    r = app.router
    r.add_post("/join", join)
    r.add_get("/connected-nodes", connected_nodes)
    r.add_get("/metrics", metrics)
    r.add_delete("/delete-node", delete_node)
    r.add_get("/choose-encrypted-model-host", choose_encrypted_model_host)
    r.add_get("/choose-model-host", choose_model_host)
    r.add_post("/search-encrypted-model", search_encrypted_model)
    r.add_post("/search-model", search_model)
    r.add_get("/search-available-models", search_available_models)
    r.add_get("/search-available-tags", search_available_tags)
    r.add_post("/search", search)
    r.add_get("/models", models)
    r.add_get("/datasets", datasets)
    r.add_get("/nodes-status", nodes_status)
    r.add_post("/aggregation/register", aggregation_register)
    r.add_delete("/aggregation/register", aggregation_unregister)
    r.add_get("/aggregation/placement", aggregation_placement)
    r.add_get("/aggregation/tree", aggregation_tree)
    r.add_get("/telemetry/slo", telemetry_slo)
    r.add_get("/healthz", healthz)
    r.add_post("/users/signup", _rbac_twin(USER_EVENTS.SIGNUP_USER))
    r.add_post("/users/login", _rbac_twin(USER_EVENTS.LOGIN_USER))
    r.add_get("/users/", _rbac_twin(USER_EVENTS.GET_ALL_USERS))
    r.add_get("/users/{id}", _rbac_twin(USER_EVENTS.GET_SPECIFIC_USER))
    r.add_put("/users/{id}/email", _rbac_twin(USER_EVENTS.PUT_EMAIL))
    r.add_put("/users/{id}/password", _rbac_twin(USER_EVENTS.PUT_PASSWORD))
    r.add_put("/users/{id}/role", _rbac_twin(USER_EVENTS.PUT_ROLE))
    r.add_delete("/users/{id}", _rbac_twin(USER_EVENTS.DELETE_USER))
    r.add_post("/roles/", _rbac_twin(ROLE_EVENTS.CREATE_ROLE))
    r.add_get("/roles/", _rbac_twin(ROLE_EVENTS.GET_ALL_ROLES))
    r.add_get("/roles/{id}", _rbac_twin(ROLE_EVENTS.GET_ROLE))
    r.add_put("/roles/{id}", _rbac_twin(ROLE_EVENTS.PUT_ROLE))
    r.add_delete("/roles/{id}", _rbac_twin(ROLE_EVENTS.DELETE_ROLE))
