"""Node registry.

Parity surface: reference ``apps/network/src/app/network/network_manager.py``
(register_new_node:11, delete_node:26, connected_nodes:44) over the
``GridNodes`` schema (``network/nodes.py:4-18``).
"""

from __future__ import annotations

from dataclasses import dataclass

from pygrid_tpu.storage.warehouse import Database, Warehouse


@dataclass
class GridNode:
    id: str = ""
    address: str = ""


class NetworkManager:
    def __init__(self, db: Database) -> None:
        self._nodes = Warehouse(GridNode, db)

    def register_new_node(self, node_id: str, node_address: str) -> bool:
        if self._nodes.contains(id=node_id):
            return False
        self._nodes.register(id=node_id, address=node_address)
        return True

    def delete_node(self, node_id: str, node_address: str) -> bool:
        if not self._nodes.contains(id=node_id, address=node_address):
            return False
        self._nodes.delete(id=node_id)
        return True

    def connected_nodes(self) -> dict[str, str]:
        return {n.id: n.address for n in self._nodes.query()}
