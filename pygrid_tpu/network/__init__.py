"""Network app — the grid directory + router.

Parity surface: reference ``apps/network/src/app/`` — app factory
(``__init__.py:91-197``), node registry (``network/network_manager.py``),
WS events join/forward/monitor-answer (``events/network.py``), node-proxy
monitor (``workers/worker.py``), HTTP fan-out routes
(``routes/network.py``), RBAC twin. One asyncio aiohttp application.
"""

from __future__ import annotations

import secrets

from pygrid_tpu.network.manager import NetworkManager
from pygrid_tpu.network.monitor import NodeProxy
from pygrid_tpu.storage.warehouse import Database
from pygrid_tpu.users import UserManager

__version__ = "0.1.0"

#: minimum nodes required to host an encrypted model (reference
#: apps/network/src/app/routes/network.py:16)
SMPC_HOST_CHUNK = 4


class NetworkContext:
    def __init__(
        self,
        network_id: str = "network",
        database_url: str = ":memory:",
        secret_key: str | None = None,
        n_replica: int = 1,
        monitor_interval: float = 15.0,
    ) -> None:
        self.id = network_id
        self.db = Database(database_url)
        self.secret_key = secret_key or secrets.token_hex(16)
        self.n_replica = n_replica
        self.monitor_interval = monitor_interval
        self.manager = NetworkManager(self.db)
        self.users = UserManager(self.db, secret_key=self.secret_key)
        #: node_id → live proxy (socket- or poll-backed)
        self.proxies: dict[str, NodeProxy] = {}
        # heartbeat-RTT burn-rate SLO, grouped per node — the monitor
        # marks nodes *degraded* (alive but eating latency budget) from
        # this engine's state, beyond the reference's alive/dead binary
        from pygrid_tpu.telemetry.slo import SLOEngine, network_objectives

        self.slo = SLOEngine(network_objectives())
        # hierarchical-aggregation placement: sub-aggregator registry +
        # worker→sub-aggregator routing (docs/AGGREGATION.md); swept for
        # liveness by the same monitor loop that heartbeats nodes
        from pygrid_tpu import telemetry
        from pygrid_tpu.network.aggregation import AggregationRegistry

        self.aggregation = AggregationRegistry()
        telemetry.recorder.register_stats_provider(
            "aggregation", self.aggregation
        )

    def proxy(self, node_id: str, address: str) -> NodeProxy:
        if node_id not in self.proxies:
            self.proxies[node_id] = NodeProxy(node_id, address)
        return self.proxies[node_id]


def create_app(
    network_id: str = "network",
    database_url: str = ":memory:",
    secret_key: str | None = None,
    n_replica: int = 1,
    monitor_interval: float = 15.0,
):
    from aiohttp import web

    from pygrid_tpu import telemetry
    from pygrid_tpu.network import routes as R
    from pygrid_tpu.network.ws import ws_handler

    ctx = NetworkContext(
        network_id,
        database_url=database_url,
        secret_key=secret_key,
        n_replica=n_replica,
        monitor_interval=monitor_interval,
    )
    app = web.Application(middlewares=[telemetry.http_middleware()])
    app["network"] = ctx
    app.router.add_get("/", ws_handler)
    R.register(app)

    async def _start_monitor(app_):
        import asyncio

        from pygrid_tpu.network.monitor import monitor_loop

        # periodic engine snapshots: placement/tree trajectory on the
        # flight-recorder ring (docs/OBSERVABILITY.md §7)
        telemetry.recorder.start_snapshots()
        app_["monitor_task"] = asyncio.get_running_loop().create_task(
            monitor_loop(ctx)
        )

    async def _stop_monitor(app_):
        task = app_.get("monitor_task")
        if task:
            task.cancel()
        import asyncio

        await asyncio.get_running_loop().run_in_executor(
            None, telemetry.recorder.stop_snapshots
        )

    app.on_startup.append(_start_monitor)
    app.on_cleanup.append(_stop_monitor)
    return app
