"""Sub-aggregator registry + worker placement for hierarchical reports.

The Network app owns the aggregation tree's SHAPE (docs/AGGREGATION.md):
sub-aggregators register here (and re-register as a heartbeat), workers
ask ``GET /aggregation/placement`` which address to report to, and the
monitor sweep expires registrations that went silent so a dead
sub-aggregator stops receiving placements within one TTL — its
subtree's workers fall back to direct node reports (the client retries
direct on any sub-aggregator failure, so placement staleness costs
latency, never a lost report).

Placement is stateless consistent hashing: ``hash(worker_id) mod
live_subaggs(node)`` — no per-worker bookkeeping to leak at 10k
workers, and a worker keeps its sub-aggregator across cycles while the
live set is stable. ``PYGRID_AGG_FANOUT`` bounds how many workers each
sub-aggregator should absorb before flushing (the sub-aggregator reads
the same knob); ``PYGRID_AGG_DEPTH`` caps tree depth for deployments
chaining sub-aggregators (a child registers its parent's address as its
upstream ``node-address`` — the registry only ever places one hop).
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field

from pygrid_tpu import telemetry

#: a registration older than this many seconds is dead for placement —
#: 3× the sub-aggregator's default re-register interval
DEFAULT_TTL_S = 15.0


@dataclass
class SubAggEntry:
    subagg_id: str
    address: str
    node_address: str
    registered_at: float = field(default_factory=time.monotonic)
    last_seen: float = field(default_factory=time.monotonic)


class AggregationRegistry:
    """Live sub-aggregators, grouped by the node (or parent
    sub-aggregator) they forward to."""

    def __init__(self, ttl_s: float | None = None) -> None:
        from pygrid_tpu.telemetry import bus

        self.ttl_s = (
            ttl_s
            if ttl_s is not None
            else bus.env_float("PYGRID_AGG_TTL_S", DEFAULT_TTL_S)
        )
        self.fanout = bus.env_int("PYGRID_AGG_FANOUT", 64)
        self.depth = bus.env_int("PYGRID_AGG_DEPTH", 2)
        self._entries: dict[str, SubAggEntry] = {}

    def register(
        self, subagg_id: str, address: str, node_address: str
    ) -> SubAggEntry:
        """Register or heartbeat one sub-aggregator (idempotent — the
        sub-aggregator re-POSTs on an interval and each POST refreshes
        ``last_seen``)."""
        now = time.monotonic()
        entry = self._entries.get(subagg_id)
        if entry is None:
            entry = SubAggEntry(
                subagg_id=str(subagg_id),
                address=str(address).rstrip("/"),
                node_address=str(node_address).rstrip("/"),
                registered_at=now,
                last_seen=now,
            )
            self._entries[subagg_id] = entry
            telemetry.incr(
                "aggregation_subaggs_total", 1, outcome="registered"
            )
        else:
            entry.address = str(address).rstrip("/")
            entry.node_address = str(node_address).rstrip("/")
            entry.last_seen = now
        return entry

    def remove(self, subagg_id: str) -> bool:
        return self._entries.pop(subagg_id, None) is not None

    def expire(self, subagg_id: str) -> bool:
        """FAULT INJECTION (pygrid_tpu/storm): back-date one entry's
        heartbeat past the TTL so the registry sees a silent death NOW
        instead of waiting out ``ttl_s`` — the kill-subagg fault uses
        this to make "stops heartbeating" and "loses placement" land in
        the same scenario tick. Production death detection stays purely
        heartbeat-driven; this only manipulates the clock, not the
        expiry logic, so ``live``/``sweep`` exercise their real paths."""
        entry = self._entries.get(subagg_id)
        if entry is None:
            return False
        entry.last_seen = time.monotonic() - self.ttl_s - 1.0
        return True

    def live(self, node_address: str | None = None) -> list[SubAggEntry]:
        """Placement-eligible entries, optionally for one upstream,
        in stable (id-sorted) order so the hash placement is
        deterministic across queries."""
        cutoff = time.monotonic() - self.ttl_s
        out = [
            e
            for e in self._entries.values()
            if e.last_seen >= cutoff
            and (
                node_address is None
                or e.node_address == node_address.rstrip("/")
            )
        ]
        return sorted(out, key=lambda e: e.subagg_id)

    def sweep(self) -> list[str]:
        """Expire silent registrations (monitor-loop cadence). Returns
        the expired ids — the heartbeat-loss path of the mid-cycle
        failure story: once expired, no new worker is placed on the
        dead sub-aggregator, and its already-placed workers' direct
        fallback covers the rest."""
        cutoff = time.monotonic() - self.ttl_s
        dead = [
            sid
            for sid, e in self._entries.items()
            if e.last_seen < cutoff
        ]
        for sid in dead:
            del self._entries[sid]
            telemetry.incr(
                "aggregation_subaggs_total", 1, outcome="expired"
            )
        return dead

    def place(
        self, node_address: str, worker_id: str
    ) -> SubAggEntry | None:
        """The sub-aggregator this worker should report to, or None for
        direct-to-node (the fallback when none are registered)."""
        live = self.live(node_address)
        if not live:
            return None
        digest = hashlib.sha256(str(worker_id).encode()).digest()
        return live[int.from_bytes(digest[:8], "big") % len(live)]

    def stats(self) -> dict:
        """Flight-recorder stats provider: the tree's live shape, so a
        network crash dump (and the periodic engine snapshots) show how
        placement looked before the failure."""
        cutoff = time.monotonic() - self.ttl_s
        live = sum(
            1 for e in self._entries.values() if e.last_seen >= cutoff
        )
        return {
            "registered": len(self._entries),
            "live": live,
            "fanout": self.fanout,
            "depth": self.depth,
            "ttl_s": self.ttl_s,
        }

    def tree(self) -> dict:
        """The topology snapshot ``GET /aggregation/tree`` serves: live
        sub-aggregators grouped under their upstream, plus the knobs."""
        cutoff = time.monotonic() - self.ttl_s
        by_upstream: dict[str, list[dict]] = {}
        for e in self._entries.values():
            by_upstream.setdefault(e.node_address, []).append(
                {
                    "id": e.subagg_id,
                    "address": e.address,
                    "live": e.last_seen >= cutoff,
                    "age_s": round(time.monotonic() - e.last_seen, 3),
                }
            )
        return {
            "fanout": self.fanout,
            "depth": self.depth,
            "ttl_s": self.ttl_s,
            "nodes": by_upstream,
        }
