"""Network WS endpoint: join / forward / monitor-answer.

Parity surface: reference ``apps/network/src/app/events/network.py`` —
``join`` registers the node's socket and starts monitoring (:25-43),
``monitor-answer`` updates the node's cached stats (:11-22), ``forward``
routes a payload to a destination node's socket (:46-61). On socket loss the
node is marked offline and reattaches on rejoin (reference
``events/socket_handler.py:36-38,63-70``).
"""

from __future__ import annotations

import json
import logging

from aiohttp import WSMsgType, web

from pygrid_tpu.network import NetworkContext
from pygrid_tpu.utils.codes import NODE_EVENTS

logger = logging.getLogger(__name__)


async def _handle(ctx: NetworkContext, message: dict, ws) -> dict | None:
    msg_type = message.get("type")
    data = message.get("data") or message

    # user/role/group WS twins — same table the Node serves (reference
    # apps/network/src/app/events/__init__.py:12-30)
    from pygrid_tpu.users.events import USER_HANDLERS

    if msg_type in USER_HANDLERS:
        return USER_HANDLERS[msg_type](ctx, message)

    if msg_type == NODE_EVENTS.JOIN:
        node_id = data.get("node-id") or data.get("id")
        address = data.get("node-address") or data.get("address")
        ctx.manager.register_new_node(node_id, address)
        proxy = ctx.proxy(node_id, address)
        proxy.socket = ws
        proxy.ping = 0.0
        return {"status": "Successfully Connected!", "id": node_id}

    if msg_type == NODE_EVENTS.MONITOR_ANSWER:
        node_id = data.get("id")
        proxy = ctx.proxies.get(node_id)
        if proxy is not None:
            proxy.update_from_answer(data)
        return None

    if msg_type == NODE_EVENTS.FORWARD:
        dest = data.get("destination")
        proxy = ctx.proxies.get(dest)
        if proxy is None or proxy.socket is None:
            return {"error": f"node {dest!r} not connected"}
        await proxy.socket.send_str(json.dumps(data.get("content")))
        return {"status": "forwarded"}

    return {"error": f"unknown event {msg_type!r}"}


async def ws_handler(request: web.Request) -> web.StreamResponse:
    ctx = request.app["network"]
    if request.headers.get("Upgrade", "").lower() != "websocket":
        return web.json_response(
            {"network_id": ctx.id, "message": "pygrid-tpu network"}
        )
    ws = web.WebSocketResponse()
    await ws.prepare(request)
    try:
        async for msg in ws:
            if msg.type != WSMsgType.TEXT:
                continue
            message = {}
            try:
                message = json.loads(msg.data)
                response = await _handle(ctx, message, ws)
            except Exception as err:  # noqa: BLE001 — protocol boundary
                response = {"error": str(err)}
            if response is not None:
                if isinstance(message, dict) and message.get("request_id"):
                    response["request_id"] = message["request_id"]
                try:
                    await ws.send_str(json.dumps(response))
                except (ConnectionError, RuntimeError):
                    break  # peer vanished mid-handler — not a server error
    finally:
        for proxy in ctx.proxies.values():
            if proxy.socket is ws:
                proxy.mark_offline()
    return ws
