"""Node health monitoring.

Parity surface: reference ``apps/network/src/app/workers/worker.py`` — a
per-node proxy tracking ping / status (online < 5s ping < busy; no contact →
offline), cached hosted models/datasets/cpu/mem, refreshed by a 15 s
heartbeat loop (``worker.py:67-86``; constants ``codes.py:51-56``). The
reference pushes a WS ``monitor`` message and waits for ``monitor-answer``;
here the loop *also* falls back to HTTP polling of the node's public
endpoints, so socketless (HTTP-joined) nodes are monitored identically.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any

from pygrid_tpu import telemetry
from pygrid_tpu.utils.codes import NODE_EVENTS

logger = logging.getLogger(__name__)

PING_THRESHOLD_MS = 5000.0  # reference WORKER_PROPERTIES.PING_THRESHOLD
ONLINE, BUSY, OFFLINE = "online", "busy", "offline"
#: alive, but burning its heartbeat-latency budget (telemetry/slo.py) —
#: the state between "fine" and "dead" the reference cannot express
DEGRADED = "degraded"


class NodeProxy:
    def __init__(self, node_id: str, address: str, socket: Any = None) -> None:
        self.id = node_id
        self.address = address
        self.socket = socket
        self.ping: float | None = None  # ms
        self.last_seen: float | None = None
        self.connected_nodes: list = []
        self.hosted_models: list = []
        self.hosted_datasets: list = []
        self.cpu_percent: float | None = None
        self.mem_usage: float | None = None
        #: reference worker.py:47-61 resolves this via an external geo-IP
        #: service; here nodes self-report it (NODE_LOCATION env / monitor
        #: answer) — no egress dependency
        self.location: str | None = None
        self._monitor_sent_at: float | None = None
        #: set by the monitor sweep from the network SLO engine's
        #: per-node heartbeat burn state (monitor_loop)
        self.degraded: bool = False

    @property
    def status(self) -> str:
        if self.ping is None:
            return OFFLINE
        if self.ping >= PING_THRESHOLD_MS:
            return BUSY
        if self.degraded:
            return DEGRADED
        return ONLINE

    def mark_offline(self) -> None:
        self.ping = None
        self.socket = None

    def monitor_sent(self) -> None:
        self._monitor_sent_at = time.monotonic()

    def update_from_answer(self, message: dict) -> None:
        """WS monitor-answer payload (reference worker.py:76-86)."""
        if self._monitor_sent_at is not None:
            self.ping = (time.monotonic() - self._monitor_sent_at) * 1000
            self._monitor_sent_at = None  # a duplicate answer must not
            # recompute ping from this consumed timestamp
            telemetry.observe(
                "heartbeat_rtt_seconds", self.ping / 1000.0,
                transport="ws", node=self.id,
            )
            telemetry.incr(
                "monitor_polls_total", 1, outcome="online", node=self.id
            )
        self.last_seen = time.time()
        self.connected_nodes = message.get("nodes") or []
        self.hosted_models = message.get("models") or []
        self.hosted_datasets = message.get("datasets") or []
        self.cpu_percent = message.get("cpu")
        self.mem_usage = message.get("mem")
        if message.get("location"):
            self.location = message["location"]


async def poll_node(proxy: NodeProxy) -> None:
    """HTTP fallback heartbeat: status + models + dataset tags. Exactly
    ONE ``monitor_polls_total`` sample per poll, decided by how the whole
    sweep ended — a 200 on /status followed by a failing /models fetch is
    one offline poll, not one of each."""
    import aiohttp

    t0 = time.monotonic()
    outcome = "offline"
    try:
        timeout = aiohttp.ClientTimeout(total=5)
        async with aiohttp.ClientSession(timeout=timeout) as session:
            async with session.get(
                proxy.address + "/data-centric/status/"
            ) as resp:
                if resp.status != 200:
                    proxy.mark_offline()
                    return
                status = await resp.json()
                if status.get("location"):
                    proxy.location = status["location"]
            proxy.ping = (time.monotonic() - t0) * 1000
            proxy.last_seen = time.time()
            async with session.get(
                proxy.address + "/data-centric/models/"
            ) as resp:
                proxy.hosted_models = (await resp.json()).get("models", [])
            async with session.get(
                proxy.address + "/data-centric/dataset-tags"
            ) as resp:
                proxy.hosted_datasets = await resp.json()
        outcome = "online"
        telemetry.observe(
            "heartbeat_rtt_seconds", proxy.ping / 1000.0,
            transport="http", node=proxy.id,
        )
    except Exception:  # noqa: BLE001 — unreachable node is a data point
        proxy.mark_offline()
    finally:
        telemetry.incr(
            "monitor_polls_total", 1, outcome=outcome, node=proxy.id
        )


async def monitor_loop(ctx) -> None:
    """15 s heartbeat across all registered nodes (reference worker.py:67-74).
    Socket-joined nodes get a WS `monitor` push; the rest are HTTP-polled."""
    import json

    while True:
        try:
            for node_id, address in ctx.manager.connected_nodes().items():
                proxy = ctx.proxy(node_id, address)
                if proxy.socket is not None:
                    try:
                        proxy.monitor_sent()
                        await proxy.socket.send_str(
                            json.dumps({"type": NODE_EVENTS.MONITOR})
                        )
                    except Exception:  # noqa: BLE001
                        proxy.mark_offline()
                else:
                    await poll_node(proxy)
            mark_degraded(ctx)
            aggregation = getattr(ctx, "aggregation", None)
            if aggregation is not None:
                # heartbeat-loss sweep: a silent sub-aggregator stops
                # receiving placements, so its subtree's workers fall
                # back to direct node reports (docs/AGGREGATION.md)
                for sid in aggregation.sweep():
                    logger.warning(
                        "sub-aggregator %s heartbeat lost — removed "
                        "from placement", sid,
                    )
        except Exception:  # noqa: BLE001 — keep the loop alive
            logger.exception("monitor sweep failed")
        await asyncio.sleep(ctx.monitor_interval)


def mark_degraded(ctx) -> None:
    """Fold the SLO engine's per-node heartbeat burn state into proxy
    status: burn > 1 means the node is answering, but slower than its
    latency budget sustains — degraded, not dead. Sweeps also snapshot
    the engine so the burn windows have data at monitor cadence. The
    verdict needs MIN_EVENTS heartbeats in the window: one slow first
    poll from a freshly joined node is not a degradation."""
    from pygrid_tpu.telemetry.slo import MIN_EVENTS

    slo = getattr(ctx, "slo", None)
    if slo is None:
        return
    # evaluate (which ticks internally) rather than bare tick: status
    # transitions are detected in evaluate, so the network's breach
    # webhooks fire at monitor cadence even when nobody scrapes
    slo.evaluate()
    burn = slo.group_burn("heartbeat_rtt", min_events=MIN_EVENTS)
    for node_id, proxy in ctx.proxies.items():
        proxy.degraded = burn.get(node_id, 0.0) > 1.0
