"""Network CLI entrypoint.

Parity surface: reference ``apps/network/src/__main__.py:10-36`` — flags
--port/--host/--start_local_db, env fallbacks (PORT, DATABASE_URL,
N_REPLICA), then serve.
"""

from __future__ import annotations

import argparse
import logging
import os


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(description="pygrid-tpu Network")
    parser.add_argument("--id", default=os.environ.get("NETWORK_ID", "network"))
    parser.add_argument(
        "--port", type=int, default=int(os.environ.get("PORT", 7000))
    )
    parser.add_argument("--host", default=os.environ.get("HOST", "0.0.0.0"))
    parser.add_argument(
        "--num_replicas",
        type=int,
        default=int(os.environ.get("N_REPLICA", 1)),
    )
    parser.add_argument("--start_local_db", action="store_true")
    return parser.parse_args(argv)


def main(argv=None) -> None:
    from aiohttp import web

    from pygrid_tpu.network import create_app

    args = parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    database_url = (
        f"network_{args.id}.db" if args.start_local_db
        else os.environ.get("DATABASE_URL", ":memory:")
    )
    app = create_app(
        args.id, database_url=database_url, n_replica=args.num_replicas
    )
    web.run_app(app, host=args.host, port=args.port)


if __name__ == "__main__":
    main()
