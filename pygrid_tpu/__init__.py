"""pygrid_tpu — a TPU-native privacy-preserving ML grid framework.

A from-scratch rebuild of the capabilities of OpenMined PyGrid (reference:
/root/reference) plus the PySyft-0.2.9 execution surface it consumes, designed
TPU-first: Plans are traced/exported XLA programs, simulated FL clients and
SMPC parties are vmapped batches of HBM-resident state on a `jax.sharding.Mesh`,
and FedAvg aggregation is a `psum` over ICI instead of a Python reduce loop.

Top-level layout (see SURVEY.md for the reference layer map this covers):

- ``serde``      wire serialization (msgpack-based, typed registry)
- ``plans``      Plan/State/PlaceHolder — traced, exported, portable programs
- ``runtime``    virtual party runtime (object store, pointers, message router)
- ``smpc``       fixed-precision ring-2^64 additive secret sharing, Beaver matmul
- ``parallel``   mesh construction, FedAvg collectives, shard_map helpers
- ``models``     model families (MLP, CNN, transformer)
- ``ops``        Pallas TPU kernels (ring matmul, ring attention)
- ``storage``    sqlite-backed Warehouse + object persistence
- ``federated``  model-centric FL coordination (cycles, controllers, managers)
- ``node``       the Node app (aiohttp HTTP + WS server)
- ``network``    the Network app (grid directory, routing, monitoring)
- ``client``     client SDK (model-centric / data-centric / FL worker clients)
- ``users``      RBAC (users, roles, groups, JWT auth)
"""

__version__ = "0.1.0"

from pygrid_tpu.utils import codes, exceptions  # noqa: F401
