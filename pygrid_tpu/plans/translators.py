"""Plan translation — the portable op-list dialect and variant registry.

Parity surface: reference ``syft_assets/plan_manager.py:119-149`` trims each
hosted plan into three stored variants (torch-op "list", TorchScript, tfjs)
via ``PlanTranslator{Default,Torchscript,Tfjs}``. Here the variants are:

- ``PlanTranslatorDefault``  -> ``"list"``: a JSON-able walk of the jaxpr —
  every equation as ``{"op", "in", "out", "params"}`` with integer SSA ids.
  Foreign clients (e.g. a JS worker) can interpret this dialect; we also ship
  a reference interpreter (:func:`run_oplist`) used by tests to prove the
  dialect is executable.
- ``PlanTranslatorXla``      -> ``"xla"``: serialized ``jax.export`` artifact
  (multi-platform StableHLO). What nodes/TPUs execute. TorchScript analog.
- ``PlanTranslatorPortable`` -> ``"code"``: human-readable jaxpr text.
  tfjs-slot analog (a display/debug portable form).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.extend.core
import jax.numpy as jnp
import numpy as np
from jax import lax

from pygrid_tpu.utils.exceptions import PlanTranslationError

# --- jaxpr -> oplist --------------------------------------------------------


def _sanitize_param(value: Any) -> Any:
    """Convert one eqn param into a wire-safe structure."""
    if isinstance(value, (bool, int, float, str, type(None))):
        return value
    if isinstance(value, (np.dtype,)) or (
        isinstance(value, type) and issubclass(value, np.generic)
    ):
        return {"__dtype__": np.dtype(value).name}
    if hasattr(value, "dtype") and hasattr(value, "shape") and not callable(value):
        return np.asarray(value)
    if isinstance(value, (tuple, list)):
        return [_sanitize_param(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _sanitize_param(v) for k, v in value.items()}
    if isinstance(value, jax.extend.core.ClosedJaxpr) or type(value).__name__ in (
        "ClosedJaxpr",
        "Jaxpr",
    ):
        closed = value
        if type(value).__name__ == "Jaxpr":  # wrap open jaxpr
            closed = jax.extend.core.ClosedJaxpr(value, ())
        return {"__jaxpr__": jaxpr_to_oplist(closed)}
    if callable(value):
        return {"__callable__": getattr(value, "__name__", repr(value))}
    return {"__repr__": repr(value)}


def jaxpr_to_oplist(closed_jaxpr) -> dict:
    """Walk a ClosedJaxpr into the portable op-list dialect."""
    jaxpr = closed_jaxpr.jaxpr
    var_ids: dict[Any, int] = {}

    def vid(var) -> int:
        if var not in var_ids:
            var_ids[var] = len(var_ids)
        return var_ids[var]

    def ref(atom) -> Any:
        # Literal values are embedded; variables become integer SSA ids.
        if hasattr(atom, "val"):
            val = atom.val
            if isinstance(val, (bool, int, float)):
                return {"lit": val}
            return {"lit_arr": np.asarray(val)}
        return {"var": vid(atom)}

    constvars = [vid(v) for v in jaxpr.constvars]
    invars = [vid(v) for v in jaxpr.invars]
    eqns = []
    for eqn in jaxpr.eqns:
        eqns.append(
            {
                "op": eqn.primitive.name,
                "in": [ref(a) for a in eqn.invars],
                "out": [vid(v) for v in eqn.outvars],
                "params": {k: _sanitize_param(v) for k, v in eqn.params.items()},
            }
        )
    outvars = [ref(a) for a in jaxpr.outvars]
    return {
        "constvars": constvars,
        "consts": [np.asarray(c) for c in closed_jaxpr.consts],
        "invars": invars,
        "eqns": eqns,
        "outvars": outvars,
    }


# --- oplist interpreter -----------------------------------------------------
#
# A reference interpreter for the "list" dialect, covering the op vocabulary
# of MLP/CNN forward+grad training plans. Exec on nodes uses the "xla"
# variant; this exists so the portable dialect is demonstrably executable
# (tests/unit/test_plans.py round-trips training plans through it).


def _dt(p):
    return np.dtype(p["__dtype__"]) if isinstance(p, dict) else np.dtype(p)


def _dims(x) -> tuple[int, ...]:
    """Coerce a sanitized dims param (list of ints / 0-d arrays) to ints."""
    if x is None:
        return ()
    return tuple(int(np.asarray(v)) for v in x)


def _tt(x):  # tuple-of-tuples from sanitized lists
    return tuple(tuple(v) if isinstance(v, list) else v for v in x)


def _dot_general(a, b, params):
    dnums = _tt(params["dimension_numbers"])
    contracting = tuple(tuple(d) for d in dnums[0])
    batch = tuple(tuple(d) for d in dnums[1])
    return lax.dot_general(a, b, dimension_numbers=(contracting, batch))


def _conv(a, b, params):
    return lax.conv_general_dilated(
        a,
        b,
        window_strides=_dims(params["window_strides"]),
        padding=[_dims(p) for p in params["padding"]],
        lhs_dilation=_dims(params["lhs_dilation"]),
        rhs_dilation=_dims(params["rhs_dilation"]),
        dimension_numbers=lax.ConvDimensionNumbers(
            *[tuple(d) for d in params["dimension_numbers"]]
        ),
        feature_group_count=params["feature_group_count"],
        batch_group_count=params["batch_group_count"],
    )


def _window_init(dtype) -> Any:
    """Identity for a max reduction at this dtype."""
    if np.issubdtype(np.dtype(dtype), np.floating):
        return -np.inf
    return np.iinfo(np.dtype(dtype)).min


def _reduce_window_max(a, p):
    return lax.reduce_window(
        a,
        jnp.asarray(_window_init(a.dtype), a.dtype),
        lax.max,
        window_dimensions=_dims(p["window_dimensions"]),
        window_strides=_dims(p["window_strides"]),
        padding=[tuple(_dims(q)) for q in p["padding"]],
        base_dilation=_dims(p["base_dilation"]),
        window_dilation=_dims(p["window_dilation"]),
    )


def _select_and_scatter_add(source, operand, p):
    """Scatter ``source`` into the positions a windowed max selects —
    which is exactly the VJP of reduce_window_max w.r.t. its operand, so
    the public autodiff machinery IS the implementation (no private
    primitive binds)."""
    sel = p.get("select_prim")
    if not (isinstance(sel, dict) and sel.get("__repr__") == "ge"):
        raise PlanTranslationError(
            f"select_and_scatter_add: unsupported select {sel!r}"
        )

    def pool(x):
        return lax.reduce_window(
            x,
            jnp.asarray(_window_init(x.dtype), x.dtype),
            lax.max,
            window_dimensions=_dims(p["window_dimensions"]),
            window_strides=_dims(p["window_strides"]),
            padding=[tuple(_dims(q)) for q in p["padding"]],
        )

    # hostile-params gate BEFORE any execution: the pooled shape must
    # match the source (and is bounded — a huge-padding envelope must
    # fail typed, not allocate inside the vjp's forward pass)
    try:
        pooled = jax.eval_shape(pool, jax.ShapeDtypeStruct(
            np.shape(operand), np.asarray(operand).dtype
        ))
    except Exception as err:  # noqa: BLE001 — remote-supplied params
        raise PlanTranslationError(
            f"select_and_scatter_add: invalid params: {err}"
        ) from err
    _bounded_elems(pooled.shape, "select_and_scatter_add (window grid)")
    if tuple(pooled.shape) != tuple(np.shape(source)):
        raise PlanTranslationError(
            f"select_and_scatter_add: source shape {np.shape(source)} "
            f"does not match window grid {pooled.shape}"
        )
    _, vjp = jax.vjp(pool, operand)
    return vjp(source)[0]


def _reduce(fn):
    def run(x, params):
        return fn(x, axis=_dims(params["axes"]))

    return run


# --- gather / scatter-add ---------------------------------------------------
#
# Emitted by the transformer family: embedding lookup (gather rows), the
# loss's take_along_axis (per-token logit pick), and their VJPs
# (scatter-add into the embedding / the one-hot-like dlogits). The
# sanitized dimension_numbers arrive as a positional list (NamedTuple
# fields, in declaration order); batching dims default to () so older
# traces without them still execute.


def _gs_dnums(p) -> tuple[tuple[int, ...], ...]:
    dims = [tuple(_dims(d)) for d in p["dimension_numbers"]]
    if len(dims) == 3:  # pre-batching-dims trace: batching dims default ()
        dims += [(), ()]
    if len(dims) != 5:
        raise PlanTranslationError(
            f"gather/scatter: unsupported dimension_numbers arity {len(dims)}"
        )
    return tuple(dims)


def _gs_mode(p) -> str:
    """'clip' | 'fill_or_drop' | 'promise_in_bounds' from the sanitized
    mode repr. PROMISE_IN_BOUNDS is executed as CLIP: out-of-bounds under
    a promise is undefined behavior in XLA, and for a REMOTE-SUPPLIED
    program clamping is the only safe refinement."""
    mode = p.get("mode")
    text = mode.get("__repr__", "") if isinstance(mode, dict) else str(mode)
    if "FILL_OR_DROP" in text:
        return "fill_or_drop"
    if "CLIP" in text or "PROMISE_IN_BOUNDS" in text or not text:
        return "clip"
    raise PlanTranslationError(f"gather/scatter: unsupported mode {text!r}")


def _gs_lax_mode(p):
    return (
        lax.GatherScatterMode.FILL_OR_DROP
        if _gs_mode(p) == "fill_or_drop"
        else lax.GatherScatterMode.CLIP
    )


def _gs_fill_value(fill, dtype):
    """jax's fill_value=None resolution (lax.gather): NaN for inexact,
    False for bool, the most negative/positive representable for
    signed/unsigned ints — mirrored so both backends agree on the wire.
    The inexact test goes through jnp.issubdtype: ml_dtypes types
    (bfloat16, float8) are inexact to jax but kind-'V' voids to numpy."""
    if fill is not None:
        return fill
    dt = np.dtype(dtype)
    if jnp.issubdtype(dt, jnp.inexact):
        return np.nan
    if dt == np.bool_:
        return False
    try:
        info = np.iinfo(dt)
    except ValueError as err:
        raise PlanTranslationError(
            f"gather: no default fill_value for dtype {dt}"
        ) from err
    return info.min if np.issubdtype(dt, np.signedinteger) else info.max


def _gather(a, idx, p):
    offs, coll, smap, ob, ib = _gs_dnums(p)
    return lax.gather(
        a,
        idx,
        lax.GatherDimensionNumbers(
            offset_dims=offs,
            collapsed_slice_dims=coll,
            start_index_map=smap,
            operand_batching_dims=ob,
            start_indices_batching_dims=ib,
        ),
        slice_sizes=_dims(p["slice_sizes"]),
        mode=_gs_lax_mode(p),
        fill_value=p.get("fill_value"),
    )


def _scatter_add(a, idx, upd, p):
    uw, ins, smap, ob, ib = _gs_dnums(p)
    return lax.scatter_add(
        a,
        idx,
        upd,
        lax.ScatterDimensionNumbers(
            update_window_dims=uw,
            inserted_window_dims=ins,
            scatter_dims_to_operand_dims=smap,
            operand_batching_dims=ob,
            scatter_indices_batching_dims=ib,
        ),
        mode=_gs_lax_mode(p),
    )


_INTERP_TABLE: dict[str, Any] = {
    "add": lambda a, b, p: jnp.add(a, b),
    "add_any": lambda a, b, p: jnp.add(a, b),  # autodiff accumulation
    "rem": lambda a, b, p: lax.rem(a, b),
    "atan2": lambda a, b, p: lax.atan2(a, b),
    "nextafter": lambda a, b, p: jnp.nextafter(a, b),
    "clamp": lambda lo, x, hi, p: lax.clamp(lo, x, hi),
    "cumsum": lambda a, p: lax.cumsum(
        a, axis=int(np.asarray(p["axis"])), reverse=bool(p.get("reverse", False))
    ),
    "sub": lambda a, b, p: jnp.subtract(a, b),
    "mul": lambda a, b, p: jnp.multiply(a, b),
    "div": lambda a, b, p: jnp.divide(a, b),
    "pow": lambda a, b, p: jnp.power(a, b),
    "max": lambda a, b, p: jnp.maximum(a, b),
    "min": lambda a, b, p: jnp.minimum(a, b),
    "and": lambda a, b, p: jnp.logical_and(a, b),
    "or": lambda a, b, p: jnp.logical_or(a, b),
    "xor": lambda a, b, p: jnp.logical_xor(a, b),
    "gt": lambda a, b, p: jnp.greater(a, b),
    "lt": lambda a, b, p: jnp.less(a, b),
    "ge": lambda a, b, p: jnp.greater_equal(a, b),
    "le": lambda a, b, p: jnp.less_equal(a, b),
    "eq": lambda a, b, p: jnp.equal(a, b),
    "ne": lambda a, b, p: jnp.not_equal(a, b),
    "neg": lambda a, p: jnp.negative(a),
    "sign": lambda a, p: jnp.sign(a),
    "abs": lambda a, p: jnp.abs(a),
    "exp": lambda a, p: jnp.exp(a),
    "log": lambda a, p: jnp.log(a),
    "tanh": lambda a, p: jnp.tanh(a),
    "sqrt": lambda a, p: jnp.sqrt(a),
    "rsqrt": lambda a, p: lax.rsqrt(a),
    "logistic": lambda a, p: jax.nn.sigmoid(a),
    "floor": lambda a, p: jnp.floor(a),
    "ceil": lambda a, p: jnp.ceil(a),
    "round": lambda a, p: jnp.round(a),
    "is_finite": lambda a, p: jnp.isfinite(a),
    "stop_gradient": lambda a, p: a,
    "copy": lambda a, p: a,
    "integer_pow": lambda a, p: lax.integer_pow(a, int(p["y"])),
    "exp2": lambda a, p: jnp.exp2(a),
    "square": lambda a, p: jnp.square(a),
    "convert_element_type": lambda a, p: lax.convert_element_type(
        a, _dt(p["new_dtype"])
    ),
    "reshape": lambda a, p: lax.reshape(a, _dims(p["new_sizes"])),
    "squeeze": lambda a, p: lax.squeeze(a, _dims(p["dimensions"])),
    "expand_dims": lambda a, p: lax.expand_dims(a, _dims(p["dimensions"])),
    "transpose": lambda a, p: lax.transpose(a, _dims(p["permutation"])),
    "broadcast_in_dim": lambda a, p: lax.broadcast_in_dim(
        a, _dims(p["shape"]), _dims(p["broadcast_dimensions"])
    ),
    "slice": lambda a, p: lax.slice(
        a,
        _dims(p["start_indices"]),
        _dims(p["limit_indices"]),
        _dims(p["strides"]) if p.get("strides") else None,
    ),
    "rev": lambda a, p: lax.rev(a, _dims(p["dimensions"])),
    "reduce_sum": _reduce(jnp.sum),
    "reduce_max": _reduce(jnp.max),
    "reduce_min": _reduce(jnp.min),
    "reduce_prod": _reduce(jnp.prod),
    "reduce_and": _reduce(jnp.all),
    "reduce_or": _reduce(jnp.any),
    "argmax": lambda a, p: jnp.argmax(a, axis=_dims(p["axes"])[0]).astype(
        _dt(p["index_dtype"])
    ),
    "argmin": lambda a, p: jnp.argmin(a, axis=_dims(p["axes"])[0]).astype(
        _dt(p["index_dtype"])
    ),
    "select_n": lambda *args: jnp.select(
        [args[0] == i for i in range(len(args[1:-1]))], list(args[1:-1])
    )
    if len(args) > 4
    else jnp.where(args[0], args[2], args[1]),
    "dot_general": _dot_general,
    "conv_general_dilated": _conv,
    "reduce_window_max": _reduce_window_max,
    "select_and_scatter_add": _select_and_scatter_add,
    "gather": _gather,
    "scatter-add": _scatter_add,
    "concatenate": lambda *args: lax.concatenate(
        list(args[:-1]), int(args[-1]["dimension"])
    ),
    "iota": lambda p: lax.broadcasted_iota(
        _dt(p["dtype"]), _dims(p["shape"]), int(p["dimension"])
    ),
    "dynamic_slice": lambda *args: lax.dynamic_slice(
        args[0], args[1:-1], _dims(args[-1]["slice_sizes"])
    ),
    "dynamic_update_slice": lambda a, u, *rest: lax.dynamic_update_slice(
        a, u, rest[:-1]
    ),
}


# --- pure-numpy interpreter table -------------------------------------------
#
# The proof that the dialect is portable OFF the XLA stack: a foreign client
# with only a ndarray library (numpy here; the same table transcribes to JS
# typed arrays) can run a grad-traced training plan. Covers the full op
# vocabulary jax.grad produces for the MLP/CNN-style plans the grid hosts
# (conformance-tested against the XLA variant in tests/unit/test_plans.py).


def _np_dot_general(a, b, params):
    dnums = _tt(params["dimension_numbers"])
    (lc, rc), (lb, rb) = (
        tuple(tuple(d) for d in dnums[0]),
        tuple(tuple(d) for d in dnums[1]),
    )
    letters = iter("abcdefghijklmnopqrstuvwxyz")
    l_sub = [None] * a.ndim
    r_sub = [None] * b.ndim
    batch = []
    for i, j in zip(lb, rb):
        ch = next(letters)
        l_sub[i] = r_sub[j] = ch
        batch.append(ch)
    for i, j in zip(lc, rc):
        ch = next(letters)
        l_sub[i] = r_sub[j] = ch
    l_free = []
    for i in range(a.ndim):
        if l_sub[i] is None:
            l_sub[i] = next(letters)
            l_free.append(l_sub[i])
    r_free = []
    for j in range(b.ndim):
        if r_sub[j] is None:
            r_sub[j] = next(letters)
            r_free.append(r_sub[j])
    spec = (
        f"{''.join(l_sub)},{''.join(r_sub)}->"
        f"{''.join(batch + l_free + r_free)}"
    )
    return np.einsum(spec, a, b)


def _np_broadcast_in_dim(a, p):
    shape, bcd = _dims(p["shape"]), _dims(p["broadcast_dimensions"])
    staged = [1] * len(shape)
    for i, d in enumerate(bcd):
        staged[d] = a.shape[i]
    return np.broadcast_to(np.reshape(a, staged), shape)


def _np_iota(p):
    shape, dim = _dims(p["shape"]), int(p["dimension"])
    ar = np.arange(shape[dim], dtype=_dt(p["dtype"]))
    view = np.reshape(
        ar, [shape[dim] if i == dim else 1 for i in range(len(shape))]
    )
    return np.broadcast_to(view, shape)


def _np_reduce(fn):
    def run(x, params):
        return fn(x, axis=_dims(params["axes"]) or None)

    return run


def _np_windows(a: np.ndarray, p: dict, pad_value) -> tuple[np.ndarray, tuple]:
    """Strided sliding windows of ``a`` per reduce-window params: returns
    (patches [out_shape… + window_dims…], padded input shape). Supports
    window_dilation via strided slicing of the window dims; base_dilation
    must be 1 (typed error — nothing in the plan corpus emits it)."""
    wd = _dims(p["window_dimensions"])
    ws = _dims(p["window_strides"])
    pads = [tuple(_dims(q)) for q in p["padding"]]
    wdil = _dims(p.get("window_dilation") or [1] * a.ndim)
    bdil = _dims(p.get("base_dilation") or [1] * a.ndim)
    if any(d != 1 for d in bdil):
        raise PlanTranslationError(
            "reduce_window: base_dilation != 1 not supported by the "
            "numpy backend"
        )
    _bounded_elems(
        [d + lo + hi for d, (lo, hi) in zip(a.shape, pads)],
        "reduce_window (padded input)",
    )
    padded = np.pad(a, pads, constant_values=pad_value)
    eff_wd = tuple((w - 1) * d + 1 for w, d in zip(wd, wdil))
    view = np.lib.stride_tricks.sliding_window_view(padded, eff_wd)
    # stride the output positions, then dilate the window dims
    out_sel = tuple(slice(None, None, s) for s in ws)
    win_sel = tuple(slice(None, None, d) for d in wdil)
    return view[out_sel + win_sel], padded.shape


def _np_reduce_window_max(a, p):
    patches, _ = _np_windows(a, p, _window_init(a.dtype))
    return patches.max(axis=tuple(range(a.ndim, 2 * a.ndim)))


def _np_select_and_scatter_add(source, operand, p):
    """Numpy twin of the maxpool VJP: route each source value to the
    first-maximum position of its window (XLA's 'ge' scan-order tie
    rule = argmax over row-major window order)."""
    sel = p.get("select_prim")
    if not (isinstance(sel, dict) and sel.get("__repr__") == "ge"):
        raise PlanTranslationError(
            f"select_and_scatter_add: unsupported select {sel!r}"
        )
    n = operand.ndim
    pads = [tuple(_dims(q)) for q in p["padding"]]
    patches, padded_shape = _np_windows(
        operand, {**p, "window_dilation": [1] * n}, _window_init(operand.dtype)
    )
    out_shape = patches.shape[:n]
    if tuple(np.shape(source)) != out_shape:
        raise PlanTranslationError(
            f"select_and_scatter_add: source shape {np.shape(source)} "
            f"does not match window grid {out_shape}"
        )
    wd = patches.shape[n:]
    flat = patches.reshape(out_shape + (-1,))
    arg = flat.argmax(axis=-1)                      # first max, row-major
    # absolute (padded) coordinates of each selected element
    win_off = np.unravel_index(arg, wd)             # n arrays, out_shape
    ws = _dims(p["window_strides"])
    out_grid = np.meshgrid(
        *[np.arange(s) for s in out_shape], indexing="ij"
    )
    scatter = np.zeros(padded_shape, dtype=source.dtype)
    idx = tuple(g * s + w for g, s, w in zip(out_grid, ws, win_off))
    np.add.at(scatter, idx, source)
    # crop the padding back off
    crop = tuple(
        slice(lo, lo + dim) for (lo, _), dim in zip(pads, operand.shape)
    )
    return scatter[crop]


def _np_conv(a, b, p):
    """conv_general_dilated on numpy: normalize to (N, C, *spatial) ×
    (O, I, *spatial) via the dimension numbers, dilate/pad explicitly,
    then one sliding-window tensordot per feature group. Covers the
    forward AND both backward convs the training plans emit (input grads
    arrive as lhs_dilation, weight grads as a transposed conv)."""
    dn = [tuple(_dims(d)) for d in p["dimension_numbers"]]
    lhs_spec, rhs_spec, out_spec = dn
    if int(p.get("batch_group_count", 1)) != 1:
        raise PlanTranslationError(
            "conv: batch_group_count != 1 not supported by numpy backend"
        )
    groups = int(p.get("feature_group_count", 1))
    a = np.transpose(a, lhs_spec)                   # [N, C, *spatial]
    b = np.transpose(b, rhs_spec)                   # [O, I, *spatial]
    nsp = a.ndim - 2
    strides = _dims(p["window_strides"])
    pads = [tuple(_dims(q)) for q in p["padding"]]
    ldil = _dims(p.get("lhs_dilation") or [1] * nsp)
    rdil = _dims(p.get("rhs_dilation") or [1] * nsp)

    def dilate(x, dil, axes):
        for ax, d in zip(axes, dil):
            if d == 1:
                continue
            shape = list(x.shape)
            shape[ax] = (shape[ax] - 1) * d + 1 if shape[ax] else 0
            _bounded_elems(shape, "conv (dilated operand)")
            out = np.zeros(shape, x.dtype)
            out[tuple(
                slice(None, None, d) if i == ax else slice(None)
                for i in range(x.ndim)
            )] = x
            x = out
        return x

    a = dilate(a, ldil, range(2, 2 + nsp))
    b = dilate(b, rdil, range(2, 2 + nsp))
    # negative padding = cropping (conv transpose emits it); a crop that
    # consumes the whole dim yields an EMPTY dim, exactly like lax
    crop = []
    for i, (lo, hi) in enumerate(pads):
        start = max(0, -lo)
        stop = max(start, a.shape[2 + i] - max(0, -hi))
        crop.append(slice(start, stop))
    a = a[(slice(None), slice(None)) + tuple(crop)]
    pos_pads = [(max(0, lo), max(0, hi)) for lo, hi in pads]
    _bounded_elems(
        list(a.shape[:2])
        + [d + lo + hi for d, (lo, hi) in zip(a.shape[2:], pos_pads)],
        "conv (padded operand)",
    )
    a = np.pad(a, [(0, 0), (0, 0)] + pos_pads)
    kernel_sp = b.shape[2:]
    view = np.lib.stride_tricks.sliding_window_view(
        a, kernel_sp, axis=tuple(range(2, 2 + nsp))
    )  # [N, C, *out_sp, *kernel_sp]
    view = view[
        (slice(None), slice(None))
        + tuple(slice(None, None, s) for s in strides)
    ]
    cin_g = a.shape[1] // groups
    cout_g = b.shape[0] // groups
    outs = []
    for g in range(groups):
        vg = view[:, g * cin_g: (g + 1) * cin_g]
        bg = b[g * cout_g: (g + 1) * cout_g]
        # [N, C, *out, *k] × [O, C, *k] → [N, *out, O]
        og = np.tensordot(
            vg, bg, axes=([1] + list(range(2 + nsp, 2 + 2 * nsp)),
                          [1] + list(range(2, 2 + nsp))),
        )
        outs.append(og)
    out = np.concatenate(outs, axis=-1)             # [N, *out_sp, O]
    out = np.moveaxis(out, -1, 1)                   # [N, O, *out_sp]
    # place result axes per out_spec: out_spec[i] = destination axis of
    # canonical axis i
    inv = np.argsort(out_spec)
    return np.transpose(out, inv)


def _np_gather(a, idx, p):
    """Numpy twin of XLA gather (one Python loop per index row — a
    reference interpreter, not a fast path). Handles offset/collapsed
    dims, batching dims, CLIP and FILL_OR_DROP modes."""
    offs, coll, smap, ob, ib = _gs_dnums(p)
    slice_sizes = _dims(p["slice_sizes"])
    mode = _gs_mode(p)
    idx = np.asarray(idx)
    a = np.asarray(a)
    if len(slice_sizes) != a.ndim:
        raise PlanTranslationError(
            f"gather: slice_sizes rank {len(slice_sizes)} != operand rank "
            f"{a.ndim}"
        )
    if idx.ndim < 1 or idx.shape[-1] != len(smap):
        raise PlanTranslationError(
            "gather: index vector dim does not match start_index_map"
        )
    for d, sz in enumerate(slice_sizes):
        if not 0 <= sz <= a.shape[d]:
            raise PlanTranslationError(
                f"gather: slice size {sz} out of range for dim {d}"
            )
    for d in (*coll, *ob):
        if slice_sizes[d] != 1:
            raise PlanTranslationError(
                f"gather: collapsed/batching dim {d} must have slice size 1"
            )
    for b in ib:
        if not 0 <= b < idx.ndim - 1:
            raise PlanTranslationError(
                f"gather: indices batching dim {b} out of range"
            )
    batch_shape = idx.shape[:-1]
    kept = [d for d in range(a.ndim) if d not in coll and d not in ob]
    if len(offs) != len(kept):
        raise PlanTranslationError("gather: offset_dims / slice-dim mismatch")
    out_rank = len(batch_shape) + len(offs)
    if any(not 0 <= d < out_rank for d in offs):
        raise PlanTranslationError("gather: offset_dims out of range")
    batch_pos = [d for d in range(out_rank) if d not in offs]
    out_shape = [0] * out_rank
    for d, size in zip(batch_pos, batch_shape):
        out_shape[d] = size
    for d, opd in zip(offs, kept):
        out_shape[d] = slice_sizes[opd]
    _bounded_elems(out_shape, "gather (output)")
    if mode == "fill_or_drop":
        # resolve the fill lazily: CLIP never consults it (and the
        # resolution can fail typed for exotic dtypes)
        out = np.full(
            out_shape,
            _gs_fill_value(p.get("fill_value"), a.dtype),
            dtype=a.dtype,
        )
    else:
        out = np.zeros(out_shape, dtype=a.dtype)  # every slot overwritten
    for pos in np.ndindex(*batch_shape):
        starts = [0] * a.ndim
        for j, opd in enumerate(smap):
            starts[opd] = int(idx[pos + (j,)])
        for opd, idim in zip(ob, ib):
            starts[opd] = pos[idim]
        oob = any(
            not 0 <= s <= a.shape[d] - slice_sizes[d]
            for d, s in enumerate(starts)
        )
        if oob:
            if mode == "fill_or_drop":
                continue  # row already holds fill_value
            starts = [
                min(max(s, 0), a.shape[d] - slice_sizes[d])
                for d, s in enumerate(starts)
            ]
        slc = a[tuple(
            slice(s, s + n) for s, n in zip(starts, slice_sizes)
        )]
        slc = np.squeeze(slc, axis=tuple(sorted((*coll, *ob))))
        sel: list[Any] = [slice(None)] * out_rank
        for d, i in zip(batch_pos, pos):
            sel[d] = i
        out[tuple(sel)] = slc
    return out


def _np_scatter_add(a, idx, upd, p):
    """Numpy twin of XLA scatter-add (same loop-per-index-row posture as
    :func:`_np_gather`); FILL_OR_DROP drops out-of-bounds updates, CLIP
    clamps them."""
    uw, ins, smap, ob, ib = _gs_dnums(p)
    mode = _gs_mode(p)
    a = np.asarray(a)
    idx = np.asarray(idx)
    upd = np.asarray(upd)
    if idx.ndim < 1 or idx.shape[-1] != len(smap):
        raise PlanTranslationError(
            "scatter-add: index vector dim does not match "
            "scatter_dims_to_operand_dims"
        )
    for b in ib:
        if not 0 <= b < idx.ndim - 1:
            raise PlanTranslationError(
                f"scatter-add: indices batching dim {b} out of range"
            )
    batch_shape = idx.shape[:-1]
    window_operand_dims = [
        d for d in range(a.ndim) if d not in ins and d not in ob
    ]
    if len(uw) != len(window_operand_dims):
        raise PlanTranslationError(
            "scatter-add: update_window_dims / operand window mismatch"
        )
    if any(not 0 <= d < upd.ndim for d in uw):
        raise PlanTranslationError(
            "scatter-add: update_window_dims out of range"
        )
    upd_batch_dims = [d for d in range(upd.ndim) if d not in uw]
    if tuple(upd.shape[d] for d in upd_batch_dims) != batch_shape:
        raise PlanTranslationError(
            "scatter-add: update batch shape does not match indices"
        )
    window_sizes = [1] * a.ndim
    for ud, opd in zip(uw, window_operand_dims):
        window_sizes[opd] = upd.shape[ud]
    if any(
        window_sizes[d] > a.shape[d] for d in range(a.ndim)
    ):
        raise PlanTranslationError(
            "scatter-add: update window exceeds operand"
        )
    out = np.array(a, copy=True)
    for pos in np.ndindex(*batch_shape):
        starts = [0] * a.ndim
        for j, opd in enumerate(smap):
            starts[opd] = int(idx[pos + (j,)])
        for opd, idim in zip(ob, ib):
            starts[opd] = pos[idim]
        oob = any(
            not 0 <= s <= a.shape[d] - window_sizes[d]
            for d, s in enumerate(starts)
        )
        if oob:
            if mode == "fill_or_drop":
                continue
            starts = [
                min(max(s, 0), a.shape[d] - window_sizes[d])
                for d, s in enumerate(starts)
            ]
        usel: list[Any] = [slice(None)] * upd.ndim
        for d, i in zip(upd_batch_dims, pos):
            usel[d] = i
        # remaining dims are uw in ascending order ↔ window_operand_dims;
        # reshape only re-inserts the size-1 inserted/batching dims
        window = np.reshape(upd[tuple(usel)], window_sizes)
        out[tuple(
            slice(s, s + n) for s, n in zip(starts, window_sizes)
        )] += window
    return out


def _np_select_n(*args):
    which, cases = args[0], list(args[1:-1])
    if len(cases) == 2 and which.dtype == np.bool_:
        return np.where(which, cases[1], cases[0])
    return np.select([which == i for i in range(len(cases))], cases)


def _np_slice(a, p):
    idx = tuple(
        slice(s, l, (st if st else None))
        for s, l, st in zip(
            _dims(p["start_indices"]),
            _dims(p["limit_indices"]),
            _dims(p["strides"]) if p.get("strides") else [None] * a.ndim,
        )
    )
    return a[idx]


def _np_dynamic_slice(*args):
    a, starts, p = args[0], args[1:-1], args[-1]
    sizes = _dims(p["slice_sizes"])
    clamped = [
        int(np.clip(int(s), 0, d - n))
        for s, d, n in zip(starts, a.shape, sizes)
    ]
    return a[tuple(slice(c, c + n) for c, n in zip(clamped, sizes))]


_NUMPY_TABLE: dict[str, Any] = {
    "add": lambda a, b, p: np.add(a, b),
    "add_any": lambda a, b, p: np.add(a, b),
    "sub": lambda a, b, p: np.subtract(a, b),
    "mul": lambda a, b, p: np.multiply(a, b),
    "div": lambda a, b, p: np.divide(a, b),
    "pow": lambda a, b, p: np.power(a, b),
    "rem": lambda a, b, p: np.fmod(a, b),  # lax.rem: C-style truncation
    "atan2": lambda a, b, p: np.arctan2(a, b),
    "nextafter": lambda a, b, p: np.nextafter(a, b),
    "max": lambda a, b, p: np.maximum(a, b),
    "min": lambda a, b, p: np.minimum(a, b),
    "and": lambda a, b, p: np.logical_and(a, b),
    "or": lambda a, b, p: np.logical_or(a, b),
    "xor": lambda a, b, p: np.logical_xor(a, b),
    "gt": lambda a, b, p: np.greater(a, b),
    "lt": lambda a, b, p: np.less(a, b),
    "ge": lambda a, b, p: np.greater_equal(a, b),
    "le": lambda a, b, p: np.less_equal(a, b),
    "eq": lambda a, b, p: np.equal(a, b),
    "ne": lambda a, b, p: np.not_equal(a, b),
    "clamp": lambda lo, x, hi, p: np.clip(x, lo, hi),
    "cumsum": lambda a, p: (
        np.flip(np.cumsum(np.flip(a, int(np.asarray(p["axis"]))),
                          int(np.asarray(p["axis"]))),
                int(np.asarray(p["axis"])))
        if bool(p.get("reverse", False))
        else np.cumsum(a, int(np.asarray(p["axis"])))
    ),
    "neg": lambda a, p: np.negative(a),
    "sign": lambda a, p: np.sign(a),
    "abs": lambda a, p: np.abs(a),
    "exp": lambda a, p: np.exp(a),
    "exp2": lambda a, p: np.exp2(a),
    "log": lambda a, p: np.log(a),
    "tanh": lambda a, p: np.tanh(a),
    "sqrt": lambda a, p: np.sqrt(a),
    "rsqrt": lambda a, p: 1.0 / np.sqrt(a),
    "logistic": lambda a, p: 1.0 / (1.0 + np.exp(-a)),
    "floor": lambda a, p: np.floor(a),
    "ceil": lambda a, p: np.ceil(a),
    "round": lambda a, p: np.round(a),  # both default to half-to-even
    "is_finite": lambda a, p: np.isfinite(a),
    "stop_gradient": lambda a, p: a,
    "copy": lambda a, p: a,
    "integer_pow": lambda a, p: a ** int(p["y"]),
    "square": lambda a, p: np.square(a),
    "convert_element_type": lambda a, p: np.asarray(a).astype(
        _dt(p["new_dtype"])
    ),
    "reshape": lambda a, p: np.reshape(a, _dims(p["new_sizes"])),
    "squeeze": lambda a, p: np.squeeze(a, axis=_dims(p["dimensions"]) or None),
    "expand_dims": lambda a, p: np.expand_dims(a, _dims(p["dimensions"])),
    "transpose": lambda a, p: np.transpose(a, _dims(p["permutation"])),
    "broadcast_in_dim": _np_broadcast_in_dim,
    "slice": _np_slice,
    "rev": lambda a, p: np.flip(a, _dims(p["dimensions"])),
    "reduce_sum": _np_reduce(np.sum),
    "reduce_max": _np_reduce(np.max),
    "reduce_min": _np_reduce(np.min),
    "reduce_prod": _np_reduce(np.prod),
    "reduce_and": _np_reduce(np.all),
    "reduce_or": _np_reduce(np.any),
    "argmax": lambda a, p: np.argmax(a, axis=_dims(p["axes"])[0]).astype(
        _dt(p["index_dtype"])
    ),
    "argmin": lambda a, p: np.argmin(a, axis=_dims(p["axes"])[0]).astype(
        _dt(p["index_dtype"])
    ),
    "select_n": _np_select_n,
    "dot_general": _np_dot_general,
    "conv_general_dilated": _np_conv,
    "reduce_window_max": _np_reduce_window_max,
    "select_and_scatter_add": _np_select_and_scatter_add,
    "gather": _np_gather,
    "scatter-add": _np_scatter_add,
    "concatenate": lambda *args: np.concatenate(
        list(args[:-1]), int(args[-1]["dimension"])
    ),
    "iota": _np_iota,
    "dynamic_slice": _np_dynamic_slice,
    "dynamic_update_slice": lambda a, u, *rest: _np_dus(a, u, rest[:-1]),
}


def _np_dus(a, u, starts):
    out = np.array(a, copy=True)
    clamped = [
        int(np.clip(int(s), 0, d - n))
        for s, d, n in zip(starts, a.shape, u.shape)
    ]
    out[tuple(slice(c, c + n) for c, n in zip(clamped, u.shape))] = u
    return out


#: sub-jaxpr wrapper primitives: executed by running the inner jaxpr
_CALL_OPS = (
    "jit", "pjit", "closed_call", "custom_jvp_call", "custom_vjp_call",
    "custom_jvp_call_jaxpr", "remat", "checkpoint", "custom_transpose_call",
)


#: ceiling on any single array an op-list may allocate (~1 GB f32): the
#: dialect executes REMOTE-SUPPLIED programs, and a few hundred bytes of
#: envelope must not be able to demand a multi-TB iota/broadcast (same
#: posture as compression.MAX_DENSE_ELEMENTS)
MAX_OPLIST_ELEMENTS = 1 << 28
#: nested call-op depth bound — a hostile envelope of self-nesting jaxprs
#: must fail typed, not exhaust the interpreter stack
MAX_OPLIST_DEPTH = 64

#: ops whose params directly size an output allocation
_ALLOC_SHAPE_PARAMS = {
    "iota": "shape",
    "broadcast_in_dim": "shape",
    "reshape": "new_sizes",
}

#: ops whose OUTPUT can dwarf their inputs even when every operand is
#: within bounds (outer-product dot_general, dilated conv, a concatenate
#: repeating one bound-passing operand many times, padding-inflated
#: window reductions) — their output shape is derived abstractly
#: (eval_shape allocates nothing) and bounded. Backend-side INTERMEDIATES
#: (padded/dilated arrays the numpy path materializes) are additionally
#: bounded at their allocation sites via _bounded_elems.
_EXPANSION_OPS = (
    "dot_general",
    "conv_general_dilated",
    "concatenate",
    "reduce_window_max",
    # gather's output (indices × slice sizes) can dwarf both operands —
    # an embedding-style gather with a hostile index count must fail the
    # bound before the backend allocates (the numpy path additionally
    # re-checks at its own allocation site)
    "gather",
    # scatter-add's output is operand-shaped (no blowup), but the
    # eval_shape pass is the typed-params gate: hostile dimension_numbers
    # must fail as PlanTranslationError on BOTH backends, not as a raw
    # IndexError/ValueError (WIRE.md §6 error contract)
    "scatter-add",
)
# select_and_scatter_add is NOT in _EXPANSION_OPS: eval_shape cannot
# trace through the jax.vjp implementation, and its output is always
# operand-shaped (already a live, bounded array); the internal pool
# shape is validated inside _select_and_scatter_add itself.


def _bounded_elems(shape, what: str) -> None:
    n = 1
    for d in shape:
        if d < 0:
            raise PlanTranslationError(f"{what}: negative dim in {shape}")
        n *= int(d)
    if n > MAX_OPLIST_ELEMENTS:
        raise PlanTranslationError(
            f"{what}: {n} elements exceeds the "
            f"{MAX_OPLIST_ELEMENTS}-element allocation bound"
        )


def _check_alloc(op: str, params: dict, invals: tuple = ()) -> None:
    key = _ALLOC_SHAPE_PARAMS.get(op)
    if key is not None and key in params:
        dims = _dims(params[key])
        n = 1
        for d in dims:
            if d < 0:
                raise PlanTranslationError(f"{op}: negative dim in {dims}")
            n *= d
        if n > MAX_OPLIST_ELEMENTS:
            raise PlanTranslationError(
                f"{op}: output of {n} elements exceeds the "
                f"{MAX_OPLIST_ELEMENTS}-element allocation bound"
            )
        return
    if op in _EXPANSION_OPS:
        jfn = _INTERP_TABLE.get(op)
        if jfn is None:
            return
        try:
            out = jax.eval_shape(lambda *xs: jfn(*xs, params), *invals)
        except PlanTranslationError:
            raise
        except Exception as err:  # noqa: BLE001 — hostile params
            raise PlanTranslationError(f"{op}: invalid params: {err}") from err
        for leaf in jax.tree_util.tree_leaves(out):
            if leaf.size > MAX_OPLIST_ELEMENTS:
                raise PlanTranslationError(
                    f"{op}: output of {leaf.size} elements exceeds the "
                    f"{MAX_OPLIST_ELEMENTS}-element allocation bound"
                )


def run_oplist(
    oplist: dict, *args: Any, backend: str = "jax", _depth: int = 0
) -> Any:
    """Interpret the portable op-list dialect. Returns the plan outputs.

    ``backend="jax"`` executes on the accelerator via jnp/lax (the
    reference interpreter); ``backend="numpy"`` executes with numpy only —
    the path proving a non-XLA client (the tfjs-analog consumer,
    reference plan_manager.py:119-149) can run a hosted training plan.

    Op-lists are remote-supplied: allocation sizes and call-nesting depth
    are bounded, and any malformed structure fails with a typed
    :class:`PlanTranslationError` (the transport frames it back to the
    sender — runtime/worker.py error contract).
    """
    if _depth > MAX_OPLIST_DEPTH:
        raise PlanTranslationError(
            f"oplist call nesting exceeds {MAX_OPLIST_DEPTH}"
        )
    if backend == "numpy":
        table, lift = _NUMPY_TABLE, np.asarray
    else:
        table, lift = _INTERP_TABLE, jnp.asarray
    env: dict[int, Any] = {}
    for cid, cval in zip(oplist["constvars"], oplist["consts"]):
        env[cid] = lift(cval)
    if len(args) != len(oplist["invars"]):
        raise PlanTranslationError(
            f"oplist expects {len(oplist['invars'])} args, got {len(args)}"
        )
    for iid, a in zip(oplist["invars"], args):
        env[iid] = lift(a)

    def read(r):
        if "var" in r:
            return env[r["var"]]
        if "lit" in r:
            return r["lit"]
        return lift(r["lit_arr"])

    for eqn in oplist["eqns"]:
        op, params = eqn["op"], eqn["params"]
        invals = [read(r) for r in eqn["in"]]
        if op in _CALL_OPS:
            inner = None
            for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                cand = params.get(key)
                if isinstance(cand, dict) and "__jaxpr__" in cand:
                    inner = cand["__jaxpr__"]
                    break
            if inner is None:
                raise PlanTranslationError(f"no inner jaxpr for {op}")
            outs = run_oplist(
                inner, *invals, backend=backend, _depth=_depth + 1
            )
            outs = outs if isinstance(outs, (list, tuple)) else [outs]
        else:
            fn = table.get(op)
            if fn is None:
                raise PlanTranslationError(
                    f"op {op!r} not in portable dialect ({backend} backend)"
                )
            _check_alloc(op, params, tuple(invals))
            outs = [fn(params)] if op == "iota" else [fn(*invals, params)]
        for oid, oval in zip(eqn["out"], outs):
            env[oid] = oval

    results = [read(r) for r in oplist["outvars"]]
    return results[0] if len(results) == 1 else tuple(results)


# --- variant registry -------------------------------------------------------


class PlanTranslatorDefault:
    variant = "list"

    @staticmethod
    def translate(plan) -> Any:
        if plan.oplist is None:
            raise PlanTranslationError("plan has no oplist (not built?)")
        return plan.oplist


class PlanTranslatorXla:
    variant = "xla"

    @staticmethod
    def translate(plan) -> Any:
        if plan.exported_blob is None:
            raise PlanTranslationError("plan has no exported XLA artifact")
        return plan.exported_blob


class PlanTranslatorPortable:
    variant = "code"

    @staticmethod
    def translate(plan) -> Any:
        return plan.code


PLAN_VARIANTS = {
    t.variant: t
    for t in (PlanTranslatorDefault, PlanTranslatorXla, PlanTranslatorPortable)
}
# wire-compat aliases for syft.js-era clients (reference routes accept
# receive_operations_as ∈ {list, torchscript, tfjs} — routes.py:228-233)
PLAN_VARIANT_ALIASES = {"torchscript": "xla", "tfjs": "code", "list": "list"}


def translate_plan(plan, variant: str) -> Any:
    variant = PLAN_VARIANT_ALIASES.get(variant, variant)
    translator = PLAN_VARIANTS.get(variant)
    if translator is None:
        raise PlanTranslationError(f"unknown plan variant {variant!r}")
    return translator.translate(plan)
