"""Plan translation — the portable op-list dialect and variant registry.

Parity surface: reference ``syft_assets/plan_manager.py:119-149`` trims each
hosted plan into three stored variants (torch-op "list", TorchScript, tfjs)
via ``PlanTranslator{Default,Torchscript,Tfjs}``. Here the variants are:

- ``PlanTranslatorDefault``  -> ``"list"``: a JSON-able walk of the jaxpr —
  every equation as ``{"op", "in", "out", "params"}`` with integer SSA ids.
  Foreign clients (e.g. a JS worker) can interpret this dialect; we also ship
  a reference interpreter (:func:`run_oplist`) used by tests to prove the
  dialect is executable.
- ``PlanTranslatorXla``      -> ``"xla"``: serialized ``jax.export`` artifact
  (multi-platform StableHLO). What nodes/TPUs execute. TorchScript analog.
- ``PlanTranslatorPortable`` -> ``"code"``: human-readable jaxpr text.
  tfjs-slot analog (a display/debug portable form).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.extend.core
import jax.numpy as jnp
import numpy as np
from jax import lax

from pygrid_tpu.utils.exceptions import PlanTranslationError

# --- jaxpr -> oplist --------------------------------------------------------


def _sanitize_param(value: Any) -> Any:
    """Convert one eqn param into a wire-safe structure."""
    if isinstance(value, (bool, int, float, str, type(None))):
        return value
    if isinstance(value, (np.dtype,)) or (
        isinstance(value, type) and issubclass(value, np.generic)
    ):
        return {"__dtype__": np.dtype(value).name}
    if hasattr(value, "dtype") and hasattr(value, "shape") and not callable(value):
        return np.asarray(value)
    if isinstance(value, (tuple, list)):
        return [_sanitize_param(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _sanitize_param(v) for k, v in value.items()}
    if isinstance(value, jax.extend.core.ClosedJaxpr) or type(value).__name__ in (
        "ClosedJaxpr",
        "Jaxpr",
    ):
        closed = value
        if type(value).__name__ == "Jaxpr":  # wrap open jaxpr
            closed = jax.extend.core.ClosedJaxpr(value, ())
        return {"__jaxpr__": jaxpr_to_oplist(closed)}
    if callable(value):
        return {"__callable__": getattr(value, "__name__", repr(value))}
    return {"__repr__": repr(value)}


def jaxpr_to_oplist(closed_jaxpr) -> dict:
    """Walk a ClosedJaxpr into the portable op-list dialect."""
    jaxpr = closed_jaxpr.jaxpr
    var_ids: dict[Any, int] = {}

    def vid(var) -> int:
        if var not in var_ids:
            var_ids[var] = len(var_ids)
        return var_ids[var]

    def ref(atom) -> Any:
        # Literal values are embedded; variables become integer SSA ids.
        if hasattr(atom, "val"):
            val = atom.val
            if isinstance(val, (bool, int, float)):
                return {"lit": val}
            return {"lit_arr": np.asarray(val)}
        return {"var": vid(atom)}

    constvars = [vid(v) for v in jaxpr.constvars]
    invars = [vid(v) for v in jaxpr.invars]
    eqns = []
    for eqn in jaxpr.eqns:
        eqns.append(
            {
                "op": eqn.primitive.name,
                "in": [ref(a) for a in eqn.invars],
                "out": [vid(v) for v in eqn.outvars],
                "params": {k: _sanitize_param(v) for k, v in eqn.params.items()},
            }
        )
    outvars = [ref(a) for a in jaxpr.outvars]
    return {
        "constvars": constvars,
        "consts": [np.asarray(c) for c in closed_jaxpr.consts],
        "invars": invars,
        "eqns": eqns,
        "outvars": outvars,
    }


# --- oplist interpreter -----------------------------------------------------
#
# A reference interpreter for the "list" dialect, covering the op vocabulary
# of MLP/CNN forward+grad training plans. Exec on nodes uses the "xla"
# variant; this exists so the portable dialect is demonstrably executable
# (tests/unit/test_plans.py round-trips training plans through it).


def _dt(p):
    return np.dtype(p["__dtype__"]) if isinstance(p, dict) else np.dtype(p)


def _dims(x) -> tuple[int, ...]:
    """Coerce a sanitized dims param (list of ints / 0-d arrays) to ints."""
    if x is None:
        return ()
    return tuple(int(np.asarray(v)) for v in x)


def _tt(x):  # tuple-of-tuples from sanitized lists
    return tuple(tuple(v) if isinstance(v, list) else v for v in x)


def _dot_general(a, b, params):
    dnums = _tt(params["dimension_numbers"])
    contracting = tuple(tuple(d) for d in dnums[0])
    batch = tuple(tuple(d) for d in dnums[1])
    return lax.dot_general(a, b, dimension_numbers=(contracting, batch))


def _conv(a, b, params):
    return lax.conv_general_dilated(
        a,
        b,
        window_strides=_dims(params["window_strides"]),
        padding=[_dims(p) for p in params["padding"]],
        lhs_dilation=_dims(params["lhs_dilation"]),
        rhs_dilation=_dims(params["rhs_dilation"]),
        dimension_numbers=lax.ConvDimensionNumbers(
            *[tuple(d) for d in params["dimension_numbers"]]
        ),
        feature_group_count=params["feature_group_count"],
        batch_group_count=params["batch_group_count"],
    )


def _reduce(fn):
    def run(x, params):
        return fn(x, axis=_dims(params["axes"]))

    return run


_INTERP_TABLE: dict[str, Any] = {
    "add": lambda a, b, p: jnp.add(a, b),
    "add_any": lambda a, b, p: jnp.add(a, b),  # autodiff accumulation
    "rem": lambda a, b, p: lax.rem(a, b),
    "atan2": lambda a, b, p: lax.atan2(a, b),
    "nextafter": lambda a, b, p: jnp.nextafter(a, b),
    "clamp": lambda lo, x, hi, p: lax.clamp(lo, x, hi),
    "cumsum": lambda a, p: lax.cumsum(
        a, axis=int(np.asarray(p["axis"])), reverse=bool(p.get("reverse", False))
    ),
    "sub": lambda a, b, p: jnp.subtract(a, b),
    "mul": lambda a, b, p: jnp.multiply(a, b),
    "div": lambda a, b, p: jnp.divide(a, b),
    "pow": lambda a, b, p: jnp.power(a, b),
    "max": lambda a, b, p: jnp.maximum(a, b),
    "min": lambda a, b, p: jnp.minimum(a, b),
    "and": lambda a, b, p: jnp.logical_and(a, b),
    "or": lambda a, b, p: jnp.logical_or(a, b),
    "xor": lambda a, b, p: jnp.logical_xor(a, b),
    "gt": lambda a, b, p: jnp.greater(a, b),
    "lt": lambda a, b, p: jnp.less(a, b),
    "ge": lambda a, b, p: jnp.greater_equal(a, b),
    "le": lambda a, b, p: jnp.less_equal(a, b),
    "eq": lambda a, b, p: jnp.equal(a, b),
    "ne": lambda a, b, p: jnp.not_equal(a, b),
    "neg": lambda a, p: jnp.negative(a),
    "sign": lambda a, p: jnp.sign(a),
    "abs": lambda a, p: jnp.abs(a),
    "exp": lambda a, p: jnp.exp(a),
    "log": lambda a, p: jnp.log(a),
    "tanh": lambda a, p: jnp.tanh(a),
    "sqrt": lambda a, p: jnp.sqrt(a),
    "rsqrt": lambda a, p: lax.rsqrt(a),
    "logistic": lambda a, p: jax.nn.sigmoid(a),
    "floor": lambda a, p: jnp.floor(a),
    "ceil": lambda a, p: jnp.ceil(a),
    "round": lambda a, p: jnp.round(a),
    "is_finite": lambda a, p: jnp.isfinite(a),
    "stop_gradient": lambda a, p: a,
    "copy": lambda a, p: a,
    "integer_pow": lambda a, p: lax.integer_pow(a, int(p["y"])),
    "exp2": lambda a, p: jnp.exp2(a),
    "square": lambda a, p: jnp.square(a),
    "convert_element_type": lambda a, p: lax.convert_element_type(
        a, _dt(p["new_dtype"])
    ),
    "reshape": lambda a, p: lax.reshape(a, _dims(p["new_sizes"])),
    "squeeze": lambda a, p: lax.squeeze(a, _dims(p["dimensions"])),
    "expand_dims": lambda a, p: lax.expand_dims(a, _dims(p["dimensions"])),
    "transpose": lambda a, p: lax.transpose(a, _dims(p["permutation"])),
    "broadcast_in_dim": lambda a, p: lax.broadcast_in_dim(
        a, _dims(p["shape"]), _dims(p["broadcast_dimensions"])
    ),
    "slice": lambda a, p: lax.slice(
        a,
        _dims(p["start_indices"]),
        _dims(p["limit_indices"]),
        _dims(p["strides"]) if p.get("strides") else None,
    ),
    "rev": lambda a, p: lax.rev(a, _dims(p["dimensions"])),
    "reduce_sum": _reduce(jnp.sum),
    "reduce_max": _reduce(jnp.max),
    "reduce_min": _reduce(jnp.min),
    "reduce_prod": _reduce(jnp.prod),
    "reduce_and": _reduce(jnp.all),
    "reduce_or": _reduce(jnp.any),
    "argmax": lambda a, p: jnp.argmax(a, axis=_dims(p["axes"])[0]).astype(
        _dt(p["index_dtype"])
    ),
    "argmin": lambda a, p: jnp.argmin(a, axis=_dims(p["axes"])[0]).astype(
        _dt(p["index_dtype"])
    ),
    "select_n": lambda *args: jnp.select(
        [args[0] == i for i in range(len(args[1:-1]))], list(args[1:-1])
    )
    if len(args) > 4
    else jnp.where(args[0], args[2], args[1]),
    "dot_general": _dot_general,
    "conv_general_dilated": _conv,
    "concatenate": lambda *args: lax.concatenate(
        list(args[:-1]), int(args[-1]["dimension"])
    ),
    "iota": lambda p: lax.broadcasted_iota(
        _dt(p["dtype"]), _dims(p["shape"]), int(p["dimension"])
    ),
    "dynamic_slice": lambda *args: lax.dynamic_slice(
        args[0], args[1:-1], _dims(args[-1]["slice_sizes"])
    ),
    "dynamic_update_slice": lambda a, u, *rest: lax.dynamic_update_slice(
        a, u, rest[:-1]
    ),
}


def run_oplist(oplist: dict, *args: Any) -> Any:
    """Interpret the portable op-list dialect. Returns the plan outputs."""
    env: dict[int, Any] = {}
    for cid, cval in zip(oplist["constvars"], oplist["consts"]):
        env[cid] = jnp.asarray(cval)
    if len(args) != len(oplist["invars"]):
        raise PlanTranslationError(
            f"oplist expects {len(oplist['invars'])} args, got {len(args)}"
        )
    for iid, a in zip(oplist["invars"], args):
        env[iid] = jnp.asarray(a)

    def read(r):
        if "var" in r:
            return env[r["var"]]
        if "lit" in r:
            return r["lit"]
        return jnp.asarray(r["lit_arr"])

    for eqn in oplist["eqns"]:
        op, params = eqn["op"], eqn["params"]
        invals = [read(r) for r in eqn["in"]]
        if op in ("jit", "pjit", "closed_call", "custom_jvp_call",
                  "custom_vjp_call", "custom_jvp_call_jaxpr", "remat",
                  "checkpoint", "custom_transpose_call"):
            inner = None
            for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                cand = params.get(key)
                if isinstance(cand, dict) and "__jaxpr__" in cand:
                    inner = cand["__jaxpr__"]
                    break
            if inner is None:
                raise PlanTranslationError(f"no inner jaxpr for {op}")
            outs = run_oplist(inner, *invals)
            outs = outs if isinstance(outs, (list, tuple)) else [outs]
        else:
            fn = _INTERP_TABLE.get(op)
            if fn is None:
                raise PlanTranslationError(f"op {op!r} not in portable dialect")
            outs = [fn(params)] if op == "iota" else [fn(*invals, params)]
        for oid, oval in zip(eqn["out"], outs):
            env[oid] = oval

    results = [read(r) for r in oplist["outvars"]]
    return results[0] if len(results) == 1 else tuple(results)


# --- variant registry -------------------------------------------------------


class PlanTranslatorDefault:
    variant = "list"

    @staticmethod
    def translate(plan) -> Any:
        if plan.oplist is None:
            raise PlanTranslationError("plan has no oplist (not built?)")
        return plan.oplist


class PlanTranslatorXla:
    variant = "xla"

    @staticmethod
    def translate(plan) -> Any:
        if plan.exported_blob is None:
            raise PlanTranslationError("plan has no exported XLA artifact")
        return plan.exported_blob


class PlanTranslatorPortable:
    variant = "code"

    @staticmethod
    def translate(plan) -> Any:
        return plan.code


PLAN_VARIANTS = {
    t.variant: t
    for t in (PlanTranslatorDefault, PlanTranslatorXla, PlanTranslatorPortable)
}
# wire-compat aliases for syft.js-era clients (reference routes accept
# receive_operations_as ∈ {list, torchscript, tfjs} — routes.py:228-233)
PLAN_VARIANT_ALIASES = {"torchscript": "xla", "tfjs": "code", "list": "list"}


def translate_plan(plan, variant: str) -> Any:
    variant = PLAN_VARIANT_ALIASES.get(variant, variant)
    translator = PLAN_VARIANTS.get(variant)
    if translator is None:
        raise PlanTranslationError(f"unknown plan variant {variant!r}")
    return translator.translate(plan)
