from pygrid_tpu.plans.placeholder import PlaceHolder  # noqa: F401
from pygrid_tpu.plans.state import State  # noqa: F401
from pygrid_tpu.plans.plan import Plan, func2plan  # noqa: F401
from pygrid_tpu.plans.translators import (  # noqa: F401
    PLAN_VARIANTS,
    PlanTranslatorDefault,
    PlanTranslatorPortable,
    PlanTranslatorXla,
    translate_plan,
)
