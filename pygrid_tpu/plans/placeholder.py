"""PlaceHolder — a stable-id slot wrapping one array.

Execution-plane parity: the syft ``PlaceHolder`` the reference builds States
from (``models/model_manager.py:80-92`` does
``State(PlaceHolder().instantiate(param))``). Here a PlaceHolder is a plain
container: an integer id stable across serde round-trips plus a host or device
array. It is deliberately *not* a tracer — under JAX, program capture is done
by ``jax.make_jaxpr``/``jax.export``, so placeholders only need to carry
checkpoint tensors and their identities.
"""

from __future__ import annotations

import secrets
from typing import Any

import numpy as np

from pygrid_tpu.serde import register_serde


def fresh_id() -> int:
    """Random 63-bit id — collision-safe across processes in a grid (a
    per-process counter would collide the moment a node deserializes a
    client's placeholders next to its own)."""
    return secrets.randbits(63)


@register_serde(name="pygrid.PlaceHolder")
class PlaceHolder:
    __slots__ = ("id", "tensor", "tags", "description")

    def __init__(
        self,
        tensor: Any = None,
        id: int | None = None,
        tags: set[str] | None = None,
        description: str = "",
    ) -> None:
        self.id = int(id) if id is not None else fresh_id()
        self.tensor = tensor
        self.tags = set(tags or ())
        self.description = description

    def instantiate(self, tensor: Any) -> "PlaceHolder":
        self.tensor = tensor
        return self

    def _bufferize(self) -> dict:
        # serde-registered wrappers (AdditiveSharingTensor, nested Plans…)
        # travel as themselves; only raw device arrays are host-coerced —
        # np.asarray on a wrapper would build an object ndarray
        tensor = self.tensor
        if tensor is not None and not hasattr(tensor, "_bufferize"):
            tensor = np.asarray(tensor)
        return {
            "id": self.id,
            "tensor": tensor,
            "tags": sorted(self.tags),
            "description": self.description,
        }

    @classmethod
    def _unbufferize(cls, data: dict) -> "PlaceHolder":
        return cls(
            tensor=data["tensor"],
            id=data["id"],
            tags=set(data["tags"]),
            description=data["description"],
        )

    def __repr__(self) -> str:
        shape = getattr(self.tensor, "shape", None)
        return f"PlaceHolder(id={self.id}, shape={shape}, tags={sorted(self.tags)})"
