"""Plan — a traced, serializable, executable program.

Execution-plane parity: syft ``Plan`` (traced op list + torchscript + tfjs
variants) as consumed by the reference PlanManager
(``syft_assets/plan_manager.py:24-59,119-149``) and built in the model-centric
example (``examples/model-centric/01-Create-plan.ipynb`` cells 16-24,
``plan.build(..., trace_autograd=True)``).

TPU-native redesign: a Plan is captured once with ``jax.make_jaxpr`` and
``jax.export`` (StableHLO), so the stored artifact is what XLA actually
compiles — no interpreter in the hot loop. Three variants mirror the
reference's list/torchscript/tfjs triple:

- ``"list"`` — portable op-list dialect (JSON-able jaxpr walk) for clients
  without an XLA runtime; see :mod:`pygrid_tpu.plans.translators`.
- ``"xla"``  — serialized ``jax.export`` artifact (multi-platform cpu+tpu
  StableHLO); the variant Nodes execute. Torchscript analog.
- ``"code"`` — human-readable jaxpr text (syft ``plan.code`` analog).

``trace_autograd=True`` has no dedicated machinery here: a JAX training step
calls ``jax.grad`` inside the traced function, so the backward pass is simply
part of the captured program.
"""

from __future__ import annotations

import uuid
from typing import Any, Callable, Sequence

import jax
import numpy as np
from jax import export as jax_export

from pygrid_tpu.plans.state import State
from pygrid_tpu.serde import register_serde
from pygrid_tpu.utils.exceptions import PlanInvalidError


def _export_platforms() -> tuple[str, ...]:
    # Export for both so a plan traced on a CPU client runs on a TPU node.
    return ("cpu", "tpu")


def _export(fn: Callable, example_args: Sequence[Any]) -> jax_export.Exported:
    import inspect

    jitted = jax.jit(fn)
    # probe the signature for the kwarg name (renamed across jax versions);
    # a try/except TypeError here would mask TypeErrors from tracing fn itself
    params = inspect.signature(jax_export.export).parameters
    kw = "platforms" if "platforms" in params else "lowering_platforms"
    return jax_export.export(jitted, **{kw: _export_platforms()})(*example_args)


@register_serde(name="pygrid.Plan")
class Plan:
    """A built plan. Call it like a function."""

    def __init__(
        self,
        name: str = "",
        id: str | None = None,
        fn: Callable | None = None,
        state: State | None = None,
        input_specs: list[dict] | None = None,
        exported_blob: bytes | None = None,
        oplist: list | None = None,
        code: str = "",
    ) -> None:
        self.name = name
        self.id = id or uuid.uuid4().hex
        self.fn = fn
        self.state = state if state is not None else State()
        #: how many trailing plan inputs are fed from ``self.state`` (syft
        #: parity: state tensors are implicit inputs appended at call time,
        #: so updating plan.state between FL rounds changes execution)
        self.n_state_inputs = 0
        self.input_specs = input_specs or []
        self.exported_blob = exported_blob
        self.oplist = oplist
        self.code = code
        self._jitted: Callable | None = None
        self._exported: jax_export.Exported | None = None
        # "built" means the wire artifacts exist — a live fn alone is not
        # built until .build() captures jaxpr + exported StableHLO.
        self.is_built = exported_blob is not None

    # --- build -------------------------------------------------------------

    def build(self, *example_args: Any) -> "Plan":
        """Trace ``fn`` on example args, capture jaxpr + exported StableHLO.

        If the plan carries a State, its tensors are appended as trailing
        inputs — callers then invoke the plan with data args only and the
        current ``self.state`` is injected at call time.
        """
        if self.fn is None:
            raise PlanInvalidError("Plan has no function to build")
        from pygrid_tpu.plans.translators import jaxpr_to_oplist

        state_tensors = [np.asarray(t) for t in self.state.tensors()]
        self.n_state_inputs = len(state_tensors)
        example_args = tuple(example_args) + tuple(state_tensors)
        closed = jax.make_jaxpr(self.fn)(*example_args)
        self.code = str(closed)
        self.oplist = jaxpr_to_oplist(closed)
        exported = _export(self.fn, example_args)
        self._exported = exported
        self.exported_blob = bytes(exported.serialize())
        self.input_specs = [
            {"shape": list(np.shape(a)), "dtype": str(np.asarray(a).dtype)}
            for a in example_args
        ]
        self.is_built = True
        return self

    # --- execute -----------------------------------------------------------

    def _callable(self) -> Callable:
        if self.fn is not None:
            if self._jitted is None:
                self._jitted = jax.jit(self.fn)
            return self._jitted
        if self._exported is None:
            if self.exported_blob is None:
                raise PlanInvalidError("Plan is not built")
            self._exported = jax_export.deserialize(bytearray(self.exported_blob))
        return self._exported.call

    def __call__(self, *args: Any):
        if self.n_state_inputs:
            args = tuple(args) + tuple(self.state.tensors())
        return self._callable()(*args)


    # --- serde -------------------------------------------------------------

    def _bufferize(self) -> dict:
        # The full plan (all variants) crosses the wire only on host upload —
        # the reference pays the same (server stores list/ts/tfjs variants,
        # plan_manager.py:24-59). Worker downloads go through
        # translate_plan(plan, variant) and carry exactly one variant
        # (routes serve receive_operations_as — reference routes.py:228-233).
        return {
            "name": self.name,
            "id": self.id,
            "state": self.state,
            "n_state_inputs": self.n_state_inputs,
            "input_specs": self.input_specs,
            "exported_blob": self.exported_blob,
            "oplist": self.oplist,
            "code": self.code,
        }

    @classmethod
    def _unbufferize(cls, data: dict) -> "Plan":
        plan = cls(
            name=data["name"],
            id=data["id"],
            state=data["state"],
            input_specs=data["input_specs"],
            exported_blob=data["exported_blob"],
            oplist=data["oplist"],
            code=data["code"],
        )
        # .get: blobs from builds predating state injection never injected
        # state, so 0 reproduces their behavior exactly
        plan.n_state_inputs = data.get("n_state_inputs", 0)
        return plan

    def __repr__(self) -> str:
        return (
            f"Plan(name={self.name!r}, id={self.id!r}, built={self.is_built}, "
            f"inputs={self.input_specs})"
        )


def func2plan(
    args_shape: Sequence[Sequence[int]],
    state: Sequence[Any] | None = None,
    args_dtypes: Sequence[Any] | None = None,
    name: str | None = None,
):
    """Decorator: trace a python function into a built :class:`Plan`.

    Parity with syft's ``@sy.func2plan(args_shape=..., state=...)`` used in
    the reference notebooks (01-Create-plan.ipynb cell 16). ``args_shape``
    gives example input shapes (zeros are used as tracing exemplars);
    ``state`` optionally attaches model parameters carried with the plan.
    """

    def decorator(fn: Callable) -> Plan:
        dtypes = list(args_dtypes or [np.float32] * len(args_shape))
        example_args = [
            np.zeros(tuple(s), dtype=d) for s, d in zip(args_shape, dtypes)
        ]
        plan = Plan(name=name or fn.__name__, fn=fn)
        if state is not None:
            plan.state = State.from_tensors(list(state))
        plan.build(*example_args)
        return plan

    return decorator
