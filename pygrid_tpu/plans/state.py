"""State — the model-parameter container that crosses the wire.

Parity surface: syft ``State`` as consumed by the reference's ModelManager
(``models/model_manager.py:80-103``): ``serialize_model_params`` wraps a list
of tensors in placeholders and protobuf-serializes; ``unserialize_model_params``
returns ``state.tensors()``. Here a State is an ordered list of
:class:`PlaceHolder` — i.e. a flattened pytree leaf list with stable ids — and
serde rides :mod:`pygrid_tpu.serde`.
"""

from __future__ import annotations

from typing import Any, Iterable, Sequence

import numpy as np

from pygrid_tpu.plans.placeholder import PlaceHolder
from pygrid_tpu.serde import deserialize, register_serde, serialize


@register_serde(name="pygrid.State")
class State:
    __slots__ = ("state_placeholders",)

    def __init__(self, state_placeholders: Iterable[PlaceHolder] = ()) -> None:
        self.state_placeholders = list(state_placeholders)

    @classmethod
    def from_tensors(cls, tensors: Sequence[Any]) -> "State":
        return cls([PlaceHolder().instantiate(t) for t in tensors])

    def tensors(self) -> list[Any]:
        return [ph.tensor for ph in self.state_placeholders]

    def _bufferize(self) -> dict:
        return {"placeholders": self.state_placeholders}

    @classmethod
    def _unbufferize(cls, data: dict) -> "State":
        return cls(data["placeholders"])

    def __len__(self) -> int:
        return len(self.state_placeholders)

    def __repr__(self) -> str:
        return f"State({self.state_placeholders!r})"


def serialize_model_params(
    params: Sequence[Any], *, bf16: bool = False
) -> bytes:
    """list-of-arrays -> wire bytes (reference model_manager.py:80-92).

    ``bf16=True`` ships float32 params as bfloat16 bit patterns (half the
    upload bytes; the FL diff path opts in via client_config)."""
    return serialize(
        State.from_tensors([np.asarray(p) for p in params]),
        bf16_floats=bf16,
    )


def unserialize_model_params(blob: bytes) -> list[np.ndarray]:
    """wire bytes -> list-of-arrays (reference model_manager.py:95-103)."""
    state = deserialize(blob)
    if not isinstance(state, State):
        raise TypeError("blob does not contain a State")
    return state.tensors()
