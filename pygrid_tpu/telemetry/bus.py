"""The process-wide telemetry event bus.

Always on, by design: there is no enable flag to forget in production,
so every code path pays the bus's cost on every call — which is why the
implementation is deliberately boring. One small lock held for a few
dict/list operations per call (no I/O, no allocation beyond the event
dict itself), a bounded ring for structured events, plain integer
counters, and fixed-bucket histograms. The budget is enforced by
``bench.bench_telemetry_overhead``: the instrumented wire round must
stay within 2% of the bare PR-1 path.

Histograms use **log-linear buckets**: a 1 / 2.5 / 5 ladder per decade
(the classic SRE latency ladder), spanning 1µs to 500s by default. Log
spacing keeps the bucket count small across nine decades; the linear
subdivision inside each decade keeps quantile estimates honest where
latencies actually cluster.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import deque
from typing import Any, Iterable

#: structured events kept in memory (oldest evicted first)
RING_SIZE = 4096

#: distinct label sets allowed per counter/histogram family before new
#: sets fold into ``{other="true"}`` — a misbehaving client cycling
#: label values (model ids, event names) must not grow /metrics without
#: bound; every fold increments ``telemetry_labels_dropped_total``
MAX_LABELSETS = 64

#: per-family cap overrides: families whose label values legitimately
#: scale with GRID SIZE (one series per node) get a higher ceiling —
#: folding node #65's heartbeat into ``other`` would silently disable
#: the per-node SLO grouping and the monitor's degraded detection
FAMILY_MAX_LABELSETS: dict[str, int] = {
    "heartbeat_rtt_seconds": 1024,
    "monitor_polls_total": 1024,
}

#: the fold target for over-cardinality label sets
_OTHER_KEY = (("other", "true"),)


def log_linear_bounds(
    lo_exp: int = -6,
    hi_exp: int = 2,
    steps: Iterable[float] = (1.0, 2.5, 5.0),
) -> list[float]:
    """Bucket upper bounds: ``step × 10^e`` for each decade — log-linear."""
    return [m * (10.0 ** e) for e in range(lo_exp, hi_exp + 1) for m in steps]


#: default bounds for seconds-valued histograms (1µs … 500s, 27 buckets)
DEFAULT_SECONDS_BOUNDS = log_linear_bounds()


class Histogram:
    """Fixed-bound histogram with a Prometheus-shaped snapshot."""

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds: Iterable[float] | None = None) -> None:
        self.bounds = sorted(bounds) if bounds else list(DEFAULT_SECONDS_BOUNDS)
        self.counts = [0] * (len(self.bounds) + 1)  # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        # le is an *inclusive* upper bound (Prometheus semantics):
        # bisect_left sends v == bound into that bound's bucket
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.sum += v
        self.count += 1

    def snapshot(self) -> dict:
        """``{"buckets": [(le, cumulative_count), ...], "sum", "count"}``
        with cumulative counts and a trailing ``+Inf`` bucket — exactly
        what ``Exposition.histogram`` renders."""
        buckets = []
        running = 0
        for le, c in zip(self.bounds, self.counts):
            running += c
            buckets.append((le, running))
        buckets.append((float("inf"), running + self.counts[-1]))
        return {"buckets": buckets, "sum": self.sum, "count": self.count}


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


#: HELP text per metric family — registered at first use, read by the
#: exporter so /metrics carries real descriptions, not just names
_FAMILY_HELP: dict[str, str] = {
    "events_total": "structured telemetry events recorded, by event name",
    "http_requests_total": "HTTP requests served, by route and status",
    "http_request_seconds": "HTTP request latency by route",
    "node_event_seconds": "WS/HTTP event handler latency by event type",
    "ws_frame_decode_seconds": "wire-v2 binary frame decode time",
    "wire_bytes_total": "bytes over the websocket wire, by direction/codec",
    "report_bytes_total": "FL diff upload bytes, by wire codec",
    "model_download_bytes_total": "FL checkpoint download bytes, by codec",
    "report_latency_seconds": "worker assign-to-report latency",
    "cycle_phase_seconds": "FL cycle phase durations, by phase",
    "cycles_completed_total": "FL cycles closed, by outcome",
    "heartbeat_rtt_seconds": "network→node heartbeat round trip, by transport",
    "monitor_polls_total": "monitor sweeps per node, by outcome",
    # continuous-batching generation engine (pygrid_tpu/serving)
    "serving_requests_total": "generation requests, by model and outcome",
    "serving_tokens_total": "generated tokens served, by model",
    "serving_compiles_total": "serving program compiles, by kind",
    "serving_ttft_seconds": "generation time-to-first-token (enqueue→token)",
    "serving_token_seconds": "per-token decode latency inside the batch",
    "serving_prefill_seconds": "per-request slot prefill (admission) time",
    "serving_queue_wait_seconds": "generation queue wait before a slot",
    "serving_batch_occupancy": "live slots per decode step",
    # paged KV cache (docs/SERVING.md): block tables + prefix sharing
    "serving_prefix_lookups_total": (
        "prompt-prefix cache lookups at admission, by model and outcome"
    ),
    "serving_prefix_tokens_saved_total": (
        "prompt tokens NOT re-prefilled thanks to prefix hits, by model"
    ),
    "serving_blocks_per_request": "KV pool blocks held per admitted request",
    # fused multi-step + speculative decode (docs/SERVING.md)
    "serving_fused_scans_total": (
        "fused multi-step decode scans dispatched, by model"
    ),
    "serving_fused_steps_total": (
        "device decode steps executed inside fused scans, by model"
    ),
    "serving_fused_wasted_steps_total": (
        "frozen row-steps burned by rows finishing mid-scan, by model"
    ),
    "serving_spec_verifies_total": (
        "speculative draft-propose + verify cycles, by model"
    ),
    "serving_spec_proposed_total": (
        "draft tokens proposed for verification, by model"
    ),
    "serving_spec_accepted_total": (
        "draft tokens accepted by the target model, by model — "
        "accepted/proposed is the per-model acceptance rate"
    ),
    "slo_webhook_posts_total": (
        "SLO breach-webhook deliveries, by objective and outcome"
    ),
    "slo_breach_detect_seconds": (
        "injected-fault to breach-detection latency, by objective "
        "(only observed when a fault is marked via slo.mark_fault)"
    ),
    # observability engine (telemetry/{profiler,recorder,slo}.py)
    "profiler_compile_seconds": "jitted-program calls that compiled, by kind",
    "profiler_execute_seconds": "jitted-program steady-state calls, by kind",
    "flightrecorder_dumps_total": "flight-recorder crash dumps, by reason",
    "flightrecorder_snapshots_total": (
        "periodic engine snapshots written to the flight-recorder ring"
    ),
    # hierarchical aggregation tree (docs/AGGREGATION.md)
    "aggregation_partials_total": (
        "partial subtree reports, by outcome (node accepts + edge flushes)"
    ),
    "aggregation_leaf_reports_total": (
        "worker reports standing behind accepted partials"
    ),
    "aggregation_partial_fold_seconds": (
        "node-side partial ingest: validate, zero-copy merge, durability"
    ),
    "aggregation_subaggs_total": (
        "sub-aggregator placement registry churn, by outcome"
    ),
    "subagg_reports_total": (
        "frames folded at a sub-aggregator, by kind (leaf/partial)"
    ),
    "subagg_flush_seconds": "one sub-aggregator upstream flush round trip",
    "telemetry_labels_dropped_total": (
        "label sets folded into {other} by the cardinality guard, by family"
    ),
}


def family_help(name: str) -> str:
    return _FAMILY_HELP.get(name, f"pygrid telemetry metric {name}")


def env_float(name: str, default: float) -> float:
    """Env knob parse shared by the observability modules: a typo'd
    value falls back to the default instead of raising — a knob must
    never brick an import or an app startup."""
    import os

    try:
        return float(os.environ[name])
    except (KeyError, TypeError, ValueError):
        return default


def env_int(name: str, default: int) -> int:
    """Integer twin of :func:`env_float`, same never-brick contract."""
    import os

    try:
        return int(os.environ[name])
    except (KeyError, TypeError, ValueError):
        return default


class TelemetryBus:
    def __init__(
        self,
        ring_size: int = RING_SIZE,
        max_labelsets: int = MAX_LABELSETS,
    ) -> None:
        self._lock = threading.Lock()
        self._events: deque[dict] = deque(maxlen=ring_size)
        self._counters: dict[tuple[str, tuple], float] = {}
        self._histograms: dict[tuple[str, tuple], Histogram] = {}
        self._max_labelsets = max_labelsets
        #: family name -> distinct label sets admitted so far
        self._labelsets: dict[str, int] = {}

    def _admit(
        self, name: str, labels_key: tuple, existing: dict
    ) -> tuple[str, tuple]:
        """Under the lock: the storage key for one sample. A family at
        its cardinality cap folds NEW label sets into ``{other="true"}``
        (and counts the fold) instead of growing /metrics forever;
        existing series and unlabeled samples always pass."""
        key = (name, labels_key)
        if not labels_key or key in existing:
            return key
        admitted = self._labelsets.get(name, 0)
        cap = FAMILY_MAX_LABELSETS.get(name, self._max_labelsets)
        if admitted >= cap:
            dropped = (
                "telemetry_labels_dropped_total", (("family", name),)
            )
            self._counters[dropped] = self._counters.get(dropped, 0) + 1
            return (name, _OTHER_KEY)
        self._labelsets[name] = admitted + 1
        return key

    # ── producers (the hot-path surface) ────────────────────────────────

    def record(self, event: str, /, **fields: Any) -> None:
        """Append a structured event to the ring and count its family.
        ``event`` is positional-only so fields named ``event`` cannot
        collide; the name key still wins in the stored entry."""
        entry = {**fields, "event": event, "ts": time.time()}
        with self._lock:
            self._events.append(entry)
            key = self._admit(
                "events_total", (("event", event),), self._counters
            )
            self._counters[key] = self._counters.get(key, 0) + 1

    def incr(self, name: str, value: float = 1, **labels: Any) -> None:
        with self._lock:
            key = self._admit(name, _label_key(labels), self._counters)
            self._counters[key] = self._counters.get(key, 0) + value

    def observe(
        self,
        name: str,
        value: float,
        bounds: Iterable[float] | None = None,
        **labels: Any,
    ) -> None:
        with self._lock:
            key = self._admit(name, _label_key(labels), self._histograms)
            hist = self._histograms.get(key)
            if hist is None:
                hist = self._histograms[key] = Histogram(bounds)
            hist.observe(value)

    # ── consumers (snapshots — never expose live internals) ─────────────

    def events(
        self, event: str | None = None, limit: int | None = None
    ) -> list[dict]:
        with self._lock:
            out = list(self._events)
        if event is not None:
            out = [e for e in out if e.get("event") == event]
        if limit is not None:
            out = out[-limit:]
        return out

    def counters(self) -> dict[tuple[str, tuple], float]:
        with self._lock:
            return dict(self._counters)

    def histograms(self) -> dict[tuple[str, tuple], dict]:
        with self._lock:
            return {k: h.snapshot() for k, h in self._histograms.items()}

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._counters.clear()
            self._histograms.clear()
            self._labelsets.clear()


#: the process-wide bus — module functions below are its bound methods,
#: so call sites stay one import + one call
BUS = TelemetryBus()

record = BUS.record
incr = BUS.incr
observe = BUS.observe
events = BUS.events
counters = BUS.counters
histograms = BUS.histograms
reset = BUS.reset
