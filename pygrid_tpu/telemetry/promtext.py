"""Strict Prometheus text-exposition parser.

The scrape-validity tests run every ``/metrics`` body through this: one
malformed line (duplicate HELP/TYPE, unescaped label value, interleaved
family groups, non-cumulative histogram buckets) fails the whole scrape
in real Prometheus, so it must fail here first. Deliberately stricter
than the wild-west of the ecosystem — this parses OUR output, and our
output has no excuse.

``parse(text)`` returns ``{family_name: Family}`` or raises
:class:`ValueError` with the offending line.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>\S+)(?: (?P<ts>-?\d+))?$"
)
_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")


@dataclass
class Family:
    name: str
    type: str = "untyped"
    help: str | None = None
    #: [(sample_name, labels_dict, value)]
    samples: list = field(default_factory=list)


def _parse_labels(raw: str, line: str) -> dict:
    """Strict label-set parse with the three escapes the format defines
    (``\\\\``, ``\\"``, ``\\n``); anything else escaped, unterminated, or
    bare is an error."""
    labels: dict[str, str] = {}
    i, n = 0, len(raw)
    while i < n:
        m = re.match(r"[a-zA-Z_][a-zA-Z0-9_]*", raw[i:])
        if m is None:
            raise ValueError(f"bad label name at {raw[i:]!r}: {line!r}")
        name = m.group(0)
        i += len(name)
        if raw[i : i + 2] != '="':
            raise ValueError(f"label {name!r} missing '=\"': {line!r}")
        i += 2
        out = []
        while True:
            if i >= n:
                raise ValueError(f"unterminated label value: {line!r}")
            c = raw[i]
            if c == "\\":
                esc = raw[i + 1 : i + 2]
                if esc == "\\":
                    out.append("\\")
                elif esc == '"':
                    out.append('"')
                elif esc == "n":
                    out.append("\n")
                else:
                    raise ValueError(f"bad escape \\{esc}: {line!r}")
                i += 2
            elif c == '"':
                i += 1
                break
            elif c == "\n":
                raise ValueError(f"raw newline in label value: {line!r}")
            else:
                out.append(c)
                i += 1
        if name in labels:
            raise ValueError(f"duplicate label {name!r}: {line!r}")
        labels[name] = "".join(out)
        if i < n:
            if raw[i] != ",":
                raise ValueError(f"junk after label value: {line!r}")
            i += 1
    return labels


def _parse_value(raw: str, line: str) -> float:
    if raw == "+Inf":
        return math.inf
    if raw == "-Inf":
        return -math.inf
    try:
        return float(raw)
    except ValueError as err:
        raise ValueError(f"bad sample value {raw!r}: {line!r}") from err


def _family_of(sample_name: str, families: dict) -> Family | None:
    """The declared family a sample belongs to: exact name, or the
    ``_bucket``/``_sum``/``_count`` members of a histogram/summary."""
    fam = families.get(sample_name)
    if fam is not None and fam.type not in ("histogram", "summary"):
        return fam
    for suffix in ("_bucket", "_sum", "_count"):
        if sample_name.endswith(suffix):
            fam = families.get(sample_name[: -len(suffix)])
            if fam is not None and fam.type in ("histogram", "summary"):
                if suffix == "_bucket" and fam.type == "summary":
                    return None
                return fam
    return families.get(sample_name)


def parse(text: str) -> dict[str, Family]:
    if not text.endswith("\n"):
        raise ValueError("exposition must end with a newline")
    families: dict[str, Family] = {}
    last_family: str | None = None
    seen_series: set[tuple] = set()
    for line in text.split("\n")[:-1]:
        if line == "":
            continue
        if line.startswith("#"):
            parts = line.split(" ", 3)
            if len(parts) < 3 or parts[0] != "#" or parts[1] not in (
                "HELP",
                "TYPE",
            ):
                raise ValueError(f"only HELP/TYPE comments allowed: {line!r}")
            name = parts[2]
            if not _NAME_RE.match(name):
                raise ValueError(f"bad metric name {name!r}: {line!r}")
            fam = families.setdefault(name, Family(name))
            if parts[1] == "HELP":
                if fam.help is not None:
                    raise ValueError(f"duplicate HELP for {name}")
                if fam.samples:
                    raise ValueError(f"HELP after samples for {name}")
                fam.help = parts[3] if len(parts) > 3 else ""
            else:
                if len(parts) < 4 or parts[3] not in _TYPES:
                    raise ValueError(f"bad TYPE: {line!r}")
                if fam.type != "untyped" or fam.samples:
                    raise ValueError(
                        f"duplicate/late TYPE for {name}: {line!r}"
                    )
                fam.type = parts[3]
            last_family = name
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"malformed sample line: {line!r}")
        labels = (
            _parse_labels(m.group("labels"), line)
            if m.group("labels")
            else {}
        )
        value = _parse_value(m.group("value"), line)
        fam = _family_of(m.group("name"), families)
        if fam is None:
            raise ValueError(
                f"sample {m.group('name')!r} has no declared family"
            )
        # family grouping: all of a family's lines must be contiguous
        if fam.name != last_family and fam.samples:
            raise ValueError(
                f"family {fam.name} interleaved with others: {line!r}"
            )
        series = (m.group("name"), tuple(sorted(labels.items())))
        if series in seen_series:
            raise ValueError(f"duplicate series: {line!r}")
        seen_series.add(series)
        fam.samples.append((m.group("name"), labels, value))
        last_family = fam.name
    for fam in families.values():
        if fam.type == "histogram":
            _check_histogram(fam)
    return families


def _check_histogram(fam: Family) -> None:
    """Per label-set (excluding ``le``): buckets must be cumulative and
    non-decreasing, carry a ``+Inf`` bucket, and agree with ``_count``."""
    groups: dict[tuple, dict] = {}
    for name, labels, value in fam.samples:
        if name == f"{fam.name}_bucket":
            if "le" not in labels:
                raise ValueError(f"{fam.name} bucket missing le label")
            rest = tuple(
                sorted((k, v) for k, v in labels.items() if k != "le")
            )
            g = groups.setdefault(rest, {"buckets": [], "count": None})
            le = _parse_value(labels["le"], f"le={labels['le']}")
            g["buckets"].append((le, value))
        elif name == f"{fam.name}_count":
            rest = tuple(sorted(labels.items()))
            g = groups.setdefault(rest, {"buckets": [], "count": None})
            g["count"] = value
    for rest, g in groups.items():
        buckets = sorted(g["buckets"])
        if not buckets or not math.isinf(buckets[-1][0]):
            raise ValueError(f"{fam.name}{dict(rest)} missing +Inf bucket")
        prev = -math.inf
        for _, c in buckets:
            if c < prev:
                raise ValueError(
                    f"{fam.name}{dict(rest)} buckets not cumulative"
                )
            prev = c
        if g["count"] is not None and buckets[-1][1] != g["count"]:
            raise ValueError(
                f"{fam.name}{dict(rest)} +Inf bucket != _count"
            )
