"""Burn-rate SLOs over the telemetry bus — the machine-readable notion
of "healthy".

An :class:`Objective` declares what good looks like for one latency
family already on the bus (``serving_ttft_seconds``,
``node_event_seconds{event=model-centric/report}``, …): a threshold and
a target fraction of events under it. The :class:`SLOEngine` evaluates
objectives the Google-SRE way — **multi-window burn rates** — instead
of point-in-time averages: the bus histograms are cumulative, so the
engine snapshots (count, good-count) per objective on a cadence and
differences snapshots across windows (default 5 min and 1 h). Burn
rate = (bad fraction over the window) / (error budget); 1.0 means the
budget is being consumed exactly as fast as it accrues.

Status policy (rendered at ``GET /telemetry/slo``, the dashboard SLO
table, and the deep ``/healthz``):

- ``ok``      — every window burn ≤ 1 and compliance at target
- ``warn``    — a window burns > 1, or lifetime compliance is below
  target (budget being eaten / ticket-worthy, not on fire)
- ``breach``  — the short window burns ≥ :data:`PAGE_BURN` on at least
  :data:`MIN_EVENTS` observations while the long window confirms
  (> :data:`CONFIRM_BURN`) — page someone. Breach is windowed-burn
  ONLY: a cumulative-compliance rule would latch deep ``/healthz`` at
  503 for hours after an incident ends
- ``no_data`` — the family has no observations yet

Thresholds/targets are env-tunable (``PYGRID_SLO_*`` —
docs/OBSERVABILITY.md §8). Grouped objectives (``group_by="node"`` on
heartbeat RTT) additionally expose per-label burn, which is how the
network monitor marks a node **degraded** — alive, but eating its
latency budget — rather than only dead.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Iterable

from pygrid_tpu.telemetry import bus

logger = logging.getLogger(__name__)

#: short-window burn that pages (the classic 14.4 = 30-day budget gone
#: in 2 days) and the long-window burn that confirms it is not a blip
PAGE_BURN = 14.4
CONFIRM_BURN = 6.0

#: minimum short-window observations before a burn verdict can breach —
#: one slow request in an otherwise-idle window must not page
MIN_EVENTS = 10

#: evaluation windows, seconds (short, long) — env-overridable
DEFAULT_WINDOWS = (300.0, 3600.0)

#: snapshots retained; at the default 15 s tick this covers > 2 h
MAX_SNAPSHOTS = 512

#: status transitions retained per engine (reaction-latency reads)
MAX_TRANSITIONS = 256


# ── fault clock ─────────────────────────────────────────────────────────
#
# Deliberately injected faults (the storm harness, pygrid_tpu/storm, or
# an operator's chaos drill) mark their injection time here; when an
# objective then transitions INTO breach, the engine measures
# injection→detection as the ``slo_breach_detect_seconds`` histogram —
# the reaction latency dashboards and storm assertions read. Unmarked
# production incidents simply never feed the histogram.

_fault_lock = threading.Lock()
_fault_marks: dict[str, float] = {}


def mark_fault(label: str = "fault", ts: float | None = None) -> float:
    """Record a deliberate fault's injection time (monotonic clock);
    returns the recorded timestamp. Re-marking a label overwrites it."""
    ts = ts if ts is not None else time.monotonic()
    with _fault_lock:
        _fault_marks[label] = ts
    return ts


def clear_fault(label: str | None = None) -> None:
    """Forget one fault mark (or all of them): the fault was cleared,
    so later breaches are not attributed to it."""
    with _fault_lock:
        if label is None:
            _fault_marks.clear()
        else:
            _fault_marks.pop(label, None)


def last_fault_ts() -> float | None:
    """The newest outstanding fault mark, or None when nothing is
    marked — breach transitions only measure detection latency against
    a fault that is actually standing."""
    with _fault_lock:
        return max(_fault_marks.values()) if _fault_marks else None


@dataclass(frozen=True)
class Objective:
    """One declarative SLO over a bus histogram family."""

    name: str
    family: str
    threshold_s: float
    target: float = 0.95
    #: label subset the family's series must match (None: every series)
    labels: dict | None = None
    #: label key to ALSO break burn out by (e.g. ``node``)
    group_by: str | None = None
    description: str = ""

    @property
    def budget(self) -> float:
        return max(1e-9, 1.0 - self.target)


#: shared env-knob parse (telemetry.bus.env_float) under the local
#: name the objective factories below read naturally
_env_float = bus.env_float


def windows_from_env() -> tuple[float, ...]:
    raw = os.environ.get("PYGRID_SLO_WINDOWS", "")
    try:
        parsed = tuple(
            float(part) for part in raw.split(",") if part.strip()
        )
    except ValueError:
        parsed = ()
    return parsed or DEFAULT_WINDOWS


def node_objectives() -> list[Objective]:
    """The node's default objectives (docs/OBSERVABILITY.md §8)."""
    return [
        Objective(
            name="serving_ttft",
            family="serving_ttft_seconds",
            threshold_s=_env_float("PYGRID_SLO_TTFT_S", 1.0),
            target=_env_float("PYGRID_SLO_TTFT_TARGET", 0.95),
            description="generation time-to-first-token under threshold",
        ),
        Objective(
            name="report_handler",
            family="node_event_seconds",
            labels={"event": "model-centric/report"},
            threshold_s=_env_float("PYGRID_SLO_REPORT_S", 0.5),
            target=_env_float("PYGRID_SLO_REPORT_TARGET", 0.99),
            description="FL report handler latency under threshold",
        ),
        Objective(
            name="cycle_round",
            family="cycle_phase_seconds",
            labels={"phase": "aggregate"},
            threshold_s=_env_float("PYGRID_SLO_CYCLE_S", 30.0),
            target=_env_float("PYGRID_SLO_CYCLE_TARGET", 0.95),
            description="FL cycle aggregation duration under threshold",
        ),
    ]


def network_objectives() -> list[Objective]:
    """The network's default objectives: heartbeat RTT, grouped per
    node so the monitor can mark individual nodes degraded."""
    return [
        Objective(
            name="heartbeat_rtt",
            family="heartbeat_rtt_seconds",
            threshold_s=_env_float("PYGRID_SLO_HEARTBEAT_S", 2.0),
            target=_env_float("PYGRID_SLO_HEARTBEAT_TARGET", 0.9),
            group_by="node",
            description="node heartbeat round trip under threshold",
        ),
    ]


@dataclass
class _Snapshot:
    ts: float
    #: objective name -> group value ("" = the ungrouped aggregate)
    #: -> (count, good)
    totals: dict[str, dict[str, tuple[int, int]]] = field(
        default_factory=dict
    )


def _good_count(snap: dict, threshold_s: float) -> int:
    """Observations ≤ threshold from a cumulative bucket snapshot: the
    count at the smallest bound ≥ threshold (bucket-resolution
    optimistic, like PromQL's histogram math — documented)."""
    for le, cumulative in snap["buckets"]:
        if le >= threshold_s:
            return cumulative
    return snap["count"]


class BreachNotifier:
    """Push-side SLO alerting: ONE webhook POST per objective STATUS
    TRANSITION (``/telemetry/slo`` is pull-only; an operator who isn't
    scraping still learns the moment an objective breaches — and the
    moment it recovers).

    Configured by ``PYGRID_SLO_WEBHOOK_URL`` (unset: the notifier is a
    no-op — the default; nothing phones anywhere unasked). Transitions
    involving ``warn``/``breach`` on either side post; ``no_data``⇄``ok``
    churn (process start, idle families) is noise and does not. Each
    objective is rate-limited (``PYGRID_SLO_WEBHOOK_MIN_S``, default
    60 s) so a flapping objective cannot flood the receiver, and every
    transition INTO ``breach`` attaches the flight recorder's crash
    dump (ring + engine snapshots + counters — the state that explains
    the breach) inline in the payload. Delivery runs on a daemon
    thread: a slow or dead receiver never blocks ``evaluate()`` (which
    handlers call on scrape paths). Outcomes land on
    ``slo_webhook_posts_total{objective, outcome}``."""

    def __init__(
        self,
        url: str | None = None,
        min_interval_s: float | None = None,
    ) -> None:
        self.url = (
            url
            if url is not None
            else os.environ.get("PYGRID_SLO_WEBHOOK_URL") or None
        )
        self.min_interval_s = (
            min_interval_s
            if min_interval_s is not None
            else bus.env_float("PYGRID_SLO_WEBHOOK_MIN_S", 60.0)
        )
        self._lock = threading.Lock()
        self._last_status: dict[str, str] = {}
        self._last_post: dict[str, float] = {}

    @staticmethod
    def _worth_posting(prev: str, status: str) -> bool:
        return "breach" in (prev, status) or "warn" in (prev, status)

    def observe(self, rows: list[dict]) -> None:
        """Feed one ``evaluate()`` result; fires POSTs for transitions.
        Cheap when unconfigured (status tracking only).

        ``_last_status`` tracks the last status the receiver was TOLD
        about: a transition suppressed by the rate limit is retried on
        the next evaluate tick (it stays pending) rather than dropped —
        otherwise a breach→ok recovery landing inside the interval
        would leave the operator's view showing a standing breach that
        ended long ago. Flapping still converges: posts are bounded to
        one per interval per objective, and the final stable state
        always goes out once the interval clears."""
        for row in rows:
            name, status = row["name"], row["status"]
            now = time.monotonic()
            rate_limited = False
            post = False
            # ONE lock acquisition per row: a read-decide-update split
            # would let two racing evaluate() callers both see the old
            # status and double-post a single transition
            with self._lock:
                prev = self._last_status.get(name)
                if prev is None or status == prev:
                    self._last_status[name] = status
                elif not self.url or not self._worth_posting(
                    prev, status
                ):
                    self._last_status[name] = status
                else:
                    last = self._last_post.get(name)
                    if last is not None and (
                        now - last < self.min_interval_s
                    ):
                        # pending, not dropped: _last_status keeps the
                        # last POSTED value so the next tick retries
                        rate_limited = True
                    else:
                        self._last_post[name] = now
                        self._last_status[name] = status
                        post = True
            if rate_limited:
                bus.incr(
                    "slo_webhook_posts_total", objective=name,
                    outcome="rate_limited",
                )
            if not post:
                continue
            payload = {
                "objective": name,
                "from": prev,
                "to": status,
                "ts": time.time(),
                "row": row,
            }
            threading.Thread(
                target=self._post,
                # the breach flight dump is BUILT on the delivery
                # thread too — evaluate() runs on scrape handlers and
                # the asyncio cadence loop, which must never wait on a
                # crash-dump disk write
                args=(name, payload, status == "breach"),
                name=f"pygrid-slo-webhook-{name}",
                daemon=True,
            ).start()

    @staticmethod
    def _flight_dump(name: str, row: dict) -> dict | None:
        """The flight recorder's crash dump for a breach, inline —
        best-effort (an unwritable flight dir must not kill alerting)."""
        try:
            from pygrid_tpu.telemetry import recorder

            recorder.note("slo.breach", objective=name)
            path = recorder.dump(f"slo_breach_{name}", snapshot=row)
            if path is None:
                return None
            with open(path, encoding="utf-8") as fh:
                return json.load(fh)
        except Exception:  # noqa: BLE001 — alert delivery > dump fidelity
            logger.exception("SLO breach flight dump failed")
            return None

    def _post(
        self, name: str, payload: dict, attach_dump: bool = False
    ) -> None:
        import urllib.request

        if attach_dump:
            payload["flight_dump"] = self._flight_dump(
                name, payload.get("row") or {}
            )
        try:
            req = urllib.request.Request(
                self.url,
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                outcome = "ok" if 200 <= resp.status < 300 else "error"
        except Exception:  # noqa: BLE001 — receiver trouble is an outcome
            logger.warning(
                "SLO webhook POST for %s failed", name, exc_info=True
            )
            outcome = "error"
        bus.incr(
            "slo_webhook_posts_total", objective=name, outcome=outcome
        )


class SLOEngine:
    """Evaluates a fixed objective set against the process bus."""

    def __init__(
        self,
        objectives: Iterable[Objective] | None = None,
        windows: tuple[float, ...] | None = None,
        source=None,
    ) -> None:
        self.objectives = list(
            objectives if objectives is not None else node_objectives()
        )
        self.windows = tuple(windows or windows_from_env())
        #: histogram source (the bus module by default; tests inject)
        self._source = source if source is not None else bus
        #: push-side alerting: one POST per objective status transition
        #: (no-op unless PYGRID_SLO_WEBHOOK_URL is set — §6)
        self.notifier = BreachNotifier()
        self._lock = threading.Lock()
        self._snaps: deque[_Snapshot] = deque(maxlen=MAX_SNAPSHOTS)
        #: minimum spacing between RETAINED snapshots: evaluate() ticks
        #: on every read (scrapes, dashboards), and unthrottled appends
        #: would evict the ring in ~30 min of 5 s polling — silently
        #: shrinking the long burn window. Rapid ticks collapse into
        #: the previous snapshot instead, so the ring always spans at
        #: least ~2× the longest window.
        self._min_gap_s = max(self.windows) / (MAX_SNAPSHOTS // 2)
        #: last status seen per objective + the transition log the storm
        #: harness reads to time reactions ("when did ttft flip to
        #: breach?") — webhook delivery state lives in the notifier and
        #: has retry semantics; this log records every flip exactly once
        self._status_seen: dict[str, str] = {}
        self._transitions: deque[dict] = deque(maxlen=MAX_TRANSITIONS)

    # ── collection ──────────────────────────────────────────────────────

    def _totals(self) -> dict[str, dict[str, tuple[int, int]]]:
        hists = self._source.histograms()
        out: dict[str, dict[str, tuple[int, int]]] = {}
        for obj in self.objectives:
            groups: dict[str, tuple[int, int]] = {}
            for (name, label_items), snap in hists.items():
                if name != obj.family:
                    continue
                labels = dict(label_items)
                if obj.labels and any(
                    labels.get(k) != v for k, v in obj.labels.items()
                ):
                    continue
                good = _good_count(snap, obj.threshold_s)
                count = snap["count"]
                keys = [""]  # "": the ungrouped aggregate
                if obj.group_by:
                    group_value = labels.get(obj.group_by)
                    if group_value:
                        keys.append(str(group_value))
                for key in keys:
                    c, g = groups.get(key, (0, 0))
                    groups[key] = (c + count, g + good)
            out[obj.name] = groups or {"": (0, 0)}
        return out

    def tick(self, now: float | None = None) -> None:
        """Append one snapshot (call on a cadence; also called by
        :meth:`evaluate` so an idle process still self-snapshots).
        A tick landing within ``_min_gap_s`` of the previous snapshot
        REPLACES it (newest data, same ring slot) unless it is the only
        anchor — read-driven ticking cannot erode window history."""
        snap = _Snapshot(
            ts=now if now is not None else time.monotonic(),
            totals=self._totals(),
        )
        with self._lock:
            # the last snapshot earns a permanent slot once it is
            # min_gap from the one before it; until then rapid ticks
            # refresh it in place
            if (
                len(self._snaps) >= 2
                and snap.ts - self._snaps[-2].ts < self._min_gap_s
            ):
                self._snaps[-1] = snap
            else:
                self._snaps.append(snap)

    # ── evaluation ──────────────────────────────────────────────────────

    def _window_delta(
        self, name: str, window: float, now: float
    ) -> tuple[int, int]:
        """(count, good) accrued inside ``[now - window, now]``."""
        with self._lock:
            snaps = list(self._snaps)
        if not snaps:
            return (0, 0)
        newest = snaps[-1].totals.get(name, {})
        cur = _sum_groups(newest)
        base: tuple[int, int] = (0, 0)
        for snap in snaps:
            if snap.ts >= now - window:
                base = _sum_groups(snap.totals.get(name, {}))
                break
        return (cur[0] - base[0], cur[1] - base[1])

    @staticmethod
    def _burn(delta: tuple[int, int], budget: float) -> float | None:
        count, good = delta
        if count <= 0:
            return None
        return ((count - good) / count) / budget

    def evaluate(self, now: float | None = None) -> list[dict]:
        """Tick, then score every objective — the ``/telemetry/slo``
        payload (see module docstring for the status policy)."""
        now = now if now is not None else time.monotonic()
        self.tick(now)
        out = []
        for obj in self.objectives:
            with self._lock:
                newest = self._snaps[-1].totals.get(obj.name, {})
            count, good = _sum_groups(newest)
            compliance = good / count if count else None
            burns: dict[str, float | None] = {}
            window_counts: dict[str, int] = {}
            # short-to-long regardless of PYGRID_SLO_WINDOWS order —
            # the dashboard's burn columns read this dict positionally
            for window in sorted(self.windows):
                label = _window_label(window)
                delta = self._window_delta(obj.name, window, now)
                window_counts[label] = delta[0]
                burns[label] = self._burn(delta, obj.budget)
            status = self._status(obj, compliance, burns, window_counts)
            self._note_transition(obj, status, now)
            row = {
                "name": obj.name,
                "family": obj.family,
                "description": obj.description,
                "threshold_s": obj.threshold_s,
                "target": obj.target,
                "events": count,
                "compliance": compliance,
                "burn": burns,
                "status": status,
            }
            if obj.group_by:
                row["by_" + obj.group_by] = self.group_burn(obj.name, now)
            out.append(row)
        try:
            self.notifier.observe(out)
        except Exception:  # noqa: BLE001 — alerting must not break reads
            logger.exception("SLO webhook notifier failed")
        return out

    def _note_transition(self, obj: Objective, status: str, now: float) -> None:
        """Log a status flip, and when an objective flips INTO breach
        while a deliberate fault is marked, observe injection→detection
        as ``slo_breach_detect_seconds`` (the reaction-latency metric)."""
        with self._lock:
            prev = self._status_seen.get(obj.name)
            if status == prev:
                return
            self._status_seen[obj.name] = status
            self._transitions.append(
                {
                    "name": obj.name,
                    "from": prev,
                    "to": status,
                    "ts": now,
                    "wall_ts": time.time(),
                }
            )
        if status == "breach" and prev != "breach":
            fault_ts = last_fault_ts()
            if fault_ts is not None and now >= fault_ts:
                self._source.observe(
                    "slo_breach_detect_seconds",
                    now - fault_ts,
                    objective=obj.name,
                )

    def transitions(self) -> list[dict]:
        """Status flips, oldest first (bounded by MAX_TRANSITIONS)."""
        with self._lock:
            return list(self._transitions)

    def _status(
        self,
        obj: Objective,
        compliance: float | None,
        burns: dict[str, float | None],
        window_counts: dict[str, int],
    ) -> str:
        if compliance is None:
            return "no_data"
        values = [b for b in burns.values() if b is not None]
        short_label = _window_label(min(self.windows))
        short = burns.get(short_label)
        long_ = burns.get(_window_label(max(self.windows)))
        # breach is WINDOWED-BURN ONLY (with MIN_EVENTS of supporting
        # traffic): lifetime compliance is cumulative and never resets,
        # so a breach rule on it would latch deep /healthz at 503 for
        # hours after an incident ends — a recovered objective must
        # read as recovered once the burn windows clear
        if (
            short is not None
            and short >= PAGE_BURN
            and window_counts.get(short_label, 0) >= MIN_EVENTS
            and (long_ is None or long_ > CONFIRM_BURN)
        ):
            return "breach"
        if any(b > 1.0 for b in values) or compliance < obj.target:
            return "warn"
        return "ok"

    def group_burn(
        self,
        name: str,
        now: float | None = None,
        min_events: int = 0,
    ) -> dict[str, float]:
        """Short-window burn per group value for a grouped objective —
        the network monitor's per-node degradation signal. Groups with
        fewer than ``min_events`` observations in the window are
        omitted: one slow heartbeat from a freshly joined node is not
        a degradation verdict."""
        now = now if now is not None else time.monotonic()
        obj = next((o for o in self.objectives if o.name == name), None)
        if obj is None or not obj.group_by:
            return {}
        window = min(self.windows)
        with self._lock:
            snaps = list(self._snaps)
        if not snaps:
            return {}
        newest = snaps[-1].totals.get(name, {})
        base: dict[str, tuple[int, int]] = {}
        for snap in snaps:
            if snap.ts >= now - window:
                base = snap.totals.get(name, {})
                break
        out: dict[str, float] = {}
        for group, (count, good) in newest.items():
            if not group:
                continue
            b_count, b_good = base.get(group, (0, 0))
            delta = (count - b_count, good - b_good)
            if delta[0] < min_events:
                continue
            burn = self._burn(delta, obj.budget)
            if burn is not None:
                out[group] = burn
        return out

    def healthy(self) -> bool:
        """The deep-health verdict: no objective in breach."""
        return all(
            row["status"] != "breach" for row in self.evaluate()
        )

    def export(self, exp) -> None:
        """SLO gauges for ``/metrics``: compliance and per-window burn
        per objective (documented in docs/OBSERVABILITY.md §8)."""
        for row in self.evaluate():
            labels = {"slo": row["name"]}
            if row["compliance"] is not None:
                exp.gauge(
                    "slo_compliance", row["compliance"],
                    "fraction of events meeting the objective", labels,
                )
            for window, burn in row["burn"].items():
                if burn is not None:
                    exp.gauge(
                        "slo_burn_rate", burn,
                        "error-budget burn rate, by window",
                        {**labels, "window": window},
                    )

    def reset(self) -> None:
        with self._lock:
            self._snaps.clear()


def _sum_groups(groups: dict[str, tuple[int, int]]) -> tuple[int, int]:
    entry = groups.get("")
    if entry is not None:
        return entry
    count = sum(c for c, _ in groups.values())
    good = sum(g for _, g in groups.values())
    return (count, good)


def _window_label(window: float) -> str:
    if window % 3600 == 0:
        return f"{int(window // 3600)}h"
    if window % 60 == 0:
        return f"{int(window // 60)}m"
    return f"{int(window)}s"
