"""Per-jit-callsite profiling and device-memory sampling.

Two questions the bench trajectory (BENCH_r0*.json) cannot answer from
aggregate counters alone:

1. **Where did a serving regression come from** — compile, execute, or
   host time? The profiler wraps every jitted program the serving
   :class:`~pygrid_tpu.serving.programs.ProgramSet` builds and splits
   wall-clock per call into *compile* (the call grew the program's jit
   cache — detected via the same ``_cache_size`` hook ``trace_count()``
   reads) and *execute* (steady-state) time, per program key. The
   wrapper never touches argument buffers after the call (the engine
   donates its cache buffers), only the clock. **Execute semantics**:
   the clock stops when the jitted call returns, WITHOUT forcing a
   device sync — on async-dispatch backends (TPU/GPU) ``execute`` is
   host dispatch time, a lower bound on device time; the end-to-end
   per-step figure including the result fetch is the engine's own
   ``serving_token_seconds`` histogram. Forcing a sync here would
   serialize the engine's host/device overlap just to measure it.
2. **Is device memory drifting** — a background sampler reads
   ``jax.local_devices()[*].memory_stats()`` on a cadence and serves
   the latest HBM gauges to ``/metrics`` (CPU backends report no
   memory_stats; the gauges are simply absent there).

Everything is off-switchable: ``PYGRID_PROFILER=off`` makes ``wrap()``
return the function unchanged and the sampler never start, so the
disabled cost is zero by construction (asserted by
``bench.bench_telemetry_overhead``). The compile-cache introspection
endpoint ``GET /telemetry/programs`` serves :func:`programs_snapshot`.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable

from pygrid_tpu.telemetry import bus

#: device-memory sampling cadence, seconds (env-overridable)
DEFAULT_SAMPLE_INTERVAL_S = 10.0


def enabled() -> bool:
    """The profiler off-switch (docs/OBSERVABILITY.md §6): the layer is
    on by default and disabled with ``PYGRID_PROFILER=off|0``."""
    return os.environ.get("PYGRID_PROFILER", "").lower() not in ("off", "0")


def cost_enabled() -> bool:
    """XLA cost attribution off-switch (``PYGRID_PROFILER_COST=off``):
    the analysis re-lowers each program once from captured avals — a
    trace, not an execution, but still work an operator may not want on
    a loaded node's telemetry endpoint."""
    return enabled() and os.environ.get(
        "PYGRID_PROFILER_COST", ""
    ).lower() not in ("off", "0")


class JitSiteProfiler:
    """Registry of jitted-program callsites and their timing splits.

    One entry per ``(model, kind, bucket)`` program — the same identity
    the serving ``ProgramSet`` compiles under. ``wrap()`` is the only
    producer; snapshots are read by ``GET /telemetry/programs``.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._programs: dict[tuple, dict] = {}
        #: program key -> (jitted fn, arg avals) captured at first call,
        #: for lazy XLA cost attribution (flops / bytes accessed); avals
        #: are ShapeDtypeStructs — metadata only, never buffer refs, so
        #: donated arguments are not pinned or touched
        self._cost_src: dict[tuple, tuple] = {}
        self._cost: dict[tuple, dict | None] = {}

    def wrap(
        self,
        fn: Callable,
        kind: str,
        bucket: int,
        model_id: str = "",
    ) -> Callable:
        """Time every call of a jitted ``fn``; classify as compile when
        the call grew the jit cache (``fn._cache_size`` — the
        ``trace_count()`` hook), execute otherwise. Returns ``fn``
        unchanged when the profiler is disabled."""
        if not enabled():
            return fn
        key = (model_id, kind, int(bucket))
        with self._lock:
            entry = self._programs.setdefault(
                key,
                {
                    "model": model_id,
                    "kind": kind,
                    "bucket": int(bucket),
                    "compiles": 0,
                    "compile_s": 0.0,
                    "hits": 0,
                    "execute_s": 0.0,
                    "traces": 0,
                },
            )
        cache_size = getattr(fn, "_cache_size", None)
        # per-WRAPPER trace watermark (not the shared entry's): a
        # re-hosted model rebuilds its programs under the same key, and
        # the fresh jit cache must still classify its first calls as
        # compiles, not hits
        seen = {"traces": 0, "calls": 0}

        def wrapped(*args: Any, **kwargs: Any):
            if seen["calls"] == 0 and hasattr(fn, "lower"):
                # capture arg AVALS (shape/dtype only) BEFORE the first
                # call — afterwards donated buffers may be consumed and
                # even metadata reads would race the donation
                self._capture_avals(key, fn, args, kwargs)
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            dt = time.perf_counter() - t0
            traces = cache_size() if callable(cache_size) else None
            with self._lock:
                if traces is not None:
                    compiled = traces > seen["traces"]
                    seen["traces"] = max(seen["traces"], traces)
                else:
                    # no cache hook: attribute the first call to compile
                    compiled = seen["calls"] == 0
                seen["calls"] += 1
                if compiled:
                    entry["compiles"] += 1
                    entry["compile_s"] += dt
                    entry["traces"] += 1
                else:
                    entry["hits"] += 1
                    entry["execute_s"] += dt
            if compiled:
                bus.observe("profiler_compile_seconds", dt, kind=kind)
            else:
                bus.observe("profiler_execute_seconds", dt, kind=kind)
            return out

        if callable(cache_size):
            wrapped._cache_size = cache_size  # keep trace_count() honest
        wrapped.__wrapped__ = fn
        return wrapped

    def _capture_avals(self, key: tuple, fn, args, kwargs) -> None:
        """Shape/dtype skeleton of a program's first-call arguments —
        enough to re-``lower`` it later for cost analysis without
        holding (or ever having held) the real buffers."""
        if not cost_enabled():
            return
        try:
            import jax

            def _aval(a):
                if hasattr(a, "shape") and hasattr(a, "dtype"):
                    return jax.ShapeDtypeStruct(a.shape, a.dtype)
                return a  # static leaf (python scalar) — pass through

            avals = jax.tree_util.tree_map(_aval, (args, kwargs))
        except Exception:  # noqa: BLE001 — attribution is best-effort
            return
        with self._lock:
            self._cost_src.setdefault(key, (fn, avals))

    def _cost_for(self, key: tuple) -> dict | None:
        """Lazy per-program XLA cost analysis (flops / bytes accessed),
        computed ONCE per program from the captured avals and cached.
        Prefers ``Lowered.cost_analysis()`` (an HLO-level estimate — a
        trace, no backend compile); falls back to
        ``Compiled.cost_analysis()`` where the lowered hook is missing.
        None when unavailable (non-jitted wrappers, disabled knob)."""
        with self._lock:
            if key in self._cost:
                return self._cost[key]
            src = self._cost_src.get(key)
        if src is None or not cost_enabled():
            return None
        result: dict | None = None
        try:
            fn, (args, kwargs) = src
            lowered = fn.lower(*args, **kwargs)
            try:
                analysis = lowered.cost_analysis()
            except Exception:  # noqa: BLE001 — hook varies by jax version
                analysis = None
            if not analysis:
                analysis = lowered.compile().cost_analysis()
            if isinstance(analysis, (list, tuple)):
                analysis = analysis[0] if analysis else None
            if isinstance(analysis, dict):
                flops = analysis.get("flops")
                nbytes = analysis.get("bytes accessed")
                result = {
                    "flops": float(flops) if flops is not None else None,
                    "bytes_accessed": float(nbytes)
                    if nbytes is not None
                    else None,
                }
        except Exception:  # noqa: BLE001 — attribution is best-effort
            result = None
        with self._lock:
            self._cost[key] = result
        return result

    def snapshot(self, include_cost: bool = False) -> list[dict]:
        """Per-program rows for ``GET /telemetry/programs``: program
        key, bucket, compile ms, hit count, execute-time split — plus,
        with ``include_cost``, the program's XLA cost analysis (flops /
        bytes accessed per call and totals over its call count), and
        rows RANKED by total bytes accessed so the heaviest device
        pressure sorts first (wall-clock alone hides a cheap-to-dispatch
        but bandwidth-hungry program)."""
        with self._lock:
            rows = [
                (key, dict(e)) for key, e in self._programs.items()
            ]
        out = []
        for key, e in rows:
            hits = e["hits"]
            row = {
                "program": f"{e['kind']}/{e['bucket']}",
                "model": e["model"],
                "kind": e["kind"],
                "bucket": e["bucket"],
                "compiles": e["compiles"],
                "compile_ms": round(e["compile_s"] * 1e3, 3),
                "hits": hits,
                "execute_ms_total": round(e["execute_s"] * 1e3, 3),
                "execute_ms_mean": round(
                    e["execute_s"] * 1e3 / hits, 4
                )
                if hits
                else None,
            }
            if include_cost:
                cost = self._cost_for(key)
                calls = hits + e["compiles"]
                row["flops"] = cost["flops"] if cost else None
                row["bytes_accessed"] = (
                    cost["bytes_accessed"] if cost else None
                )
                row["bytes_accessed_total"] = (
                    cost["bytes_accessed"] * calls
                    if cost and cost["bytes_accessed"] is not None
                    else None
                )
                row["flops_total"] = (
                    cost["flops"] * calls
                    if cost and cost["flops"] is not None
                    else None
                )
            out.append(row)
        if include_cost:
            return sorted(
                out,
                key=lambda r: (
                    -(r.get("bytes_accessed_total") or 0.0),
                    r["model"], r["kind"], r["bucket"],
                ),
            )
        return sorted(
            out, key=lambda r: (r["model"], r["kind"], r["bucket"])
        )

    def reset(self) -> None:
        with self._lock:
            self._programs.clear()
            self._cost_src.clear()
            self._cost.clear()


class DeviceMemorySampler:
    """Background thread sampling device memory on a cadence.

    ``memory_stats()`` is a host-side XLA client call (no device sync),
    but ``/metrics`` should not pay even that per scrape under load —
    the sampler keeps the latest reading and the exporter serves it."""

    def __init__(self, interval_s: float | None = None) -> None:
        if interval_s is None:
            # fallback-on-typo parse: this constructor runs at module
            # load (for MEMORY), so a bad env var must not brick imports
            interval_s = bus.env_float(
                "PYGRID_PROFILER_INTERVAL_S", DEFAULT_SAMPLE_INTERVAL_S
            )
        self.interval_s = interval_s
        self._lock = threading.Lock()
        self._latest: list[dict] = []
        self._sampled_at: float | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        #: start()/stop() pairs outstanding — several apps in one
        #: process (the test grid) share this sampler; the thread stops
        #: only when the LAST app cleans up
        self._starts = 0

    @staticmethod
    def sample_once() -> list[dict]:
        """One synchronous read of every local device's memory stats.
        Devices without the hook (CPU) contribute nothing; a failing
        backend yields an empty sample rather than an exception."""
        try:
            import jax

            devices = jax.local_devices()
        except Exception:  # noqa: BLE001 — no backend is a valid state
            return []
        out = []
        for d in devices:
            try:
                stats = d.memory_stats()
            except Exception:  # noqa: BLE001 — per-device hook optional
                stats = None
            if not stats:
                continue
            out.append(
                {
                    "device": str(getattr(d, "id", len(out))),
                    "platform": getattr(d, "platform", "unknown"),
                    "bytes_in_use": stats.get("bytes_in_use"),
                    "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                    "bytes_limit": stats.get("bytes_limit"),
                }
            )
        return out

    def latest(self) -> list[dict]:
        """The most recent background sample — NEVER samples inline:
        the reader may be the aiohttp event loop, and a cold
        ``import jax`` there would stall every socket. Empty until the
        sampler thread's first pass (it samples immediately on start)."""
        with self._lock:
            return [dict(s) for s in self._latest]

    def age_s(self) -> float | None:
        """Seconds since the last background sample (None before the
        first) — an age far beyond ``interval_s`` means the sampler
        stalled, which the gauges alone cannot show."""
        with self._lock:
            if self._sampled_at is None:
                return None
            return time.monotonic() - self._sampled_at

    def start(self) -> None:
        """Acquire the sampler. The refcount moves even when the
        profiler is disabled (only the thread spawn is gated), so every
        app's start()/stop() pair stays balanced — a disabled app's
        cleanup must never steal a live app's hold on the thread."""
        with self._lock:
            self._starts += 1
            if not enabled():
                return
            if (
                self._thread is not None
                and self._thread.is_alive()
                and not self._stop.is_set()
            ):
                return
            # no live sampling thread — or the live one is a stop()-
            # signalled straggler whose join timed out (it exits at its
            # next wait on ITS OWN captured event); spawn a fresh
            # sampler with a fresh event either way
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._loop,
                args=(self._stop,),
                name="pygrid-memory-sampler",
                daemon=True,
            )
            self._thread.start()

    def stop(self) -> None:
        """Release one start(); the thread stops when the last holder
        releases (apps in one process share the sampler)."""
        with self._lock:
            self._starts = max(0, self._starts - 1)
            if self._starts > 0:
                return
            thread = self._thread
            self._stop.set()
        if thread is not None:
            thread.join(timeout=2)

    def _loop(self, stop: threading.Event) -> None:
        while True:
            sample = self.sample_once()  # first pass BEFORE the wait
            with self._lock:
                self._latest = sample
                self._sampled_at = time.monotonic()
            if stop.wait(self.interval_s):
                return


#: process-wide instances — same posture as the telemetry bus
PROFILER = JitSiteProfiler()
MEMORY = DeviceMemorySampler()

wrap = PROFILER.wrap
programs_snapshot = PROFILER.snapshot


def export_device_memory(exp) -> None:
    """Write the latest device-memory gauges into an Exposition (called
    by the node ``/metrics`` handler). No-op when disabled or when the
    backend has no memory_stats (CPU)."""
    if not enabled():
        return
    for sample in MEMORY.latest():
        labels = {
            "device": sample["device"],
            "platform": sample["platform"],
        }
        for kind in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            value = sample.get(kind)
            if value is not None:
                exp.gauge(
                    "device_memory_bytes",
                    value,
                    "device (HBM) memory from jax memory_stats, by kind",
                    {**labels, "kind": kind},
                )


def reset() -> None:
    PROFILER.reset()
