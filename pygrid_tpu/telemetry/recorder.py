"""The flight recorder — a black box for postmortems.

Live ``/metrics`` scrapes answer "how is the grid doing *now*"; they
answer nothing once the interesting moment has passed. The recorder
keeps a process-wide bounded ring of notable moments (engine snapshots,
handler exceptions, bus annotations) and, on a trigger, writes one
self-contained **crash dump**: the ring, the telemetry bus's recent
structured events (spans included), every registered subsystem's live
stats, and the trigger's own snapshot — redacted, JSON-round-trippable,
and bounded on disk.

Triggers (docs/OBSERVABILITY.md §7):

- an unhandled WS/HTTP handler exception (``node/events.py`` dispatch
  boundary),
- a serving-engine ``_fail_all`` (every queued/live request failed),
- an operator's ``POST /telemetry/dump``.

Dumps land in ``PYGRID_FLIGHT_DIR`` (default: a ``pygrid-flight``
directory under the system temp dir), pruned to the newest
:data:`MAX_DUMPS` files, rate-limited per reason so an exception storm
produces one dump, not thousands. Every write increments
``flightrecorder_dumps_total{reason=…}``.

Redaction is structural: any mapping key that looks credential-like
(token/password/secret/…, see :data:`_REDACT_KEYS`) has its value
replaced before serialization — a dump must be shareable with an
operator channel without leaking a worker's request key or a session
token.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
import weakref
from collections import deque
from typing import Any

from pygrid_tpu.telemetry import bus

#: ring entries kept in memory (oldest evicted first)
RING_SIZE = 512

#: newest dump files kept on disk; older ones are pruned at write time
MAX_DUMPS = 20

#: bus ring events embedded in a dump
DUMP_EVENT_LIMIT = 256

#: flight-dump payload schema version (docs/OBSERVABILITY.md §7). The
#: dump's top-level shape — reason/ts/error/snapshot/ring/events/stats/
#: counters — is a STABLE machine-readable contract: replay tooling
#: (pygrid_tpu/storm/replay.py) and external consumers key on it. Bump
#: only when an existing key changes shape or meaning; ADDING keys is
#: compatible and does not bump it.
SCHEMA_VERSION = 1

#: default seconds between dumps *per reason* (env-overridable)
DEFAULT_MIN_INTERVAL_S = 30.0

#: lowercase substrings that mark a mapping key as credential-bearing
_REDACT_KEYS = (
    "token", "password", "secret", "request_key", "authorization",
    "auth", "jwt", "api_key", "private_key",
)

_REDACTED = "[redacted]"

#: strings/bytes longer than this are truncated in dumps (a dump is a
#: postmortem index, not a payload archive)
_MAX_STR = 2048


def enabled() -> bool:
    """The recorder off-switch: ``PYGRID_FLIGHT=off|0`` turns ring
    appends and automatic dumps into no-ops (the operator's explicit
    ``dump(force=True)`` still works — asking for a dump IS consent)."""
    return os.environ.get("PYGRID_FLIGHT", "").lower() not in ("off", "0")


def flight_dir() -> str:
    """The crash-dump directory: ``PYGRID_FLIGHT_DIR`` or a stable
    tempdir fallback, created on demand."""
    path = os.environ.get("PYGRID_FLIGHT_DIR") or os.path.join(
        tempfile.gettempdir(), "pygrid-flight"
    )
    os.makedirs(path, exist_ok=True)
    return path


def redact(value: Any) -> Any:
    """Recursively copy ``value`` with credential-keyed fields replaced
    and oversized strings truncated; non-JSON leaves become ``repr``."""
    if isinstance(value, dict):
        out = {}
        for k, v in value.items():
            key = str(k)
            if any(m in key.lower() for m in _REDACT_KEYS):
                out[key] = _REDACTED
            else:
                out[key] = redact(v)
        return out
    if isinstance(value, (list, tuple)):
        return [redact(v) for v in value]
    if isinstance(value, (bytes, bytearray, memoryview)):
        return f"<{len(value)} bytes>"
    if isinstance(value, str):
        return value if len(value) <= _MAX_STR else value[:_MAX_STR] + "…"
    if isinstance(value, (int, float, bool)) or value is None:
        return value
    return repr(value)


class FlightRecorder:
    def __init__(self, ring_size: int = RING_SIZE) -> None:
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=ring_size)
        self._last_dump: dict[str, float] = {}
        self._seq = 0  # uniquifies dump filenames within one millisecond
        #: name -> weakref to an object with a ``stats()`` method; the
        #: weakref keeps the recorder from pinning a closed app's
        #: serving manager (tests build hundreds of contexts)
        self._providers: dict[str, weakref.ref] = {}

    # ── producers ───────────────────────────────────────────────────────

    def note(self, kind: str, /, **fields: Any) -> None:
        """Append one moment to the ring — cheap enough for per-request
        paths (one lock, one dict; a no-op when disabled)."""
        if not enabled():
            return
        entry = {**fields, "kind": kind, "ts": time.time()}
        with self._lock:
            self._ring.append(entry)

    def register_stats_provider(self, name: str, obj: Any) -> None:
        """Snapshot ``obj.stats()`` into every future dump (held by
        weakref; dead providers are pruned at dump time)."""
        with self._lock:
            self._providers[name] = weakref.ref(obj)

    # ── consumers ───────────────────────────────────────────────────────

    def ring(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def _min_interval(self) -> float:
        return bus.env_float(
            "PYGRID_FLIGHT_MIN_INTERVAL_S", DEFAULT_MIN_INTERVAL_S
        )

    def _provider_stats(self) -> dict:
        with self._lock:
            providers = dict(self._providers)
        out = {}
        dead = []
        for name, ref in providers.items():
            obj = ref()
            if obj is None:
                dead.append(name)
                continue
            try:
                out[name] = obj.stats()
            except Exception as err:  # noqa: BLE001 — best-effort capture
                out[name] = {"error": str(err)}
        if dead:
            with self._lock:
                for name in dead:
                    if self._providers.get(name) is not None and (
                        self._providers[name]() is None
                    ):
                        del self._providers[name]
        return out

    def should_dump(self, reason: str) -> bool:
        """Cheap peek (no state change): would a ``dump(reason)`` write
        right now? The exception-storm path checks this BEFORE building
        snapshots or spawning a writer thread — the whole point of the
        rate limit is that the storm path costs one timestamp compare."""
        if not enabled():
            return False
        with self._lock:
            last = self._last_dump.get(reason)
        return last is None or (
            time.monotonic() - last >= self._min_interval()
        )

    def dump(
        self,
        reason: str,
        snapshot: Any = None,
        error: BaseException | str | None = None,
        force: bool = False,
        snapshot_redacted: bool = False,
    ) -> str | None:
        """Write one crash dump; returns its path, or None when the
        per-reason rate limit (or the off-switch) suppressed the write
        (``force=True`` — the operator's POST — always writes).
        ``snapshot_redacted`` marks a snapshot :func:`redact` already
        processed (the ``dump_soon`` path) so it is not walked twice."""
        if not force and not enabled():
            return None
        now = time.monotonic()
        with self._lock:
            last = self._last_dump.get(reason)
            if not force and last is not None and (
                now - last < self._min_interval()
            ):
                return None
            # RESERVE the slot now (check-then-act would let every
            # trigger arriving during this write's few ms pass the
            # limiter and write its own dump); rolled back on a failed
            # write so a full disk doesn't suppress the next attempt
            self._last_dump[reason] = now
        payload = {
            "schema_version": SCHEMA_VERSION,
            "reason": reason,
            "ts": time.time(),
            "error": str(error) if error is not None else None,
            "snapshot": (
                snapshot if snapshot_redacted else redact(snapshot)
            ),
            "ring": redact(self.ring()),
            "events": redact(bus.events(limit=DUMP_EVENT_LIMIT)),
            "stats": redact(self._provider_stats()),
            "counters": {
                _counter_label(name, labels): value
                for (name, labels), value in sorted(bus.counters().items())
            },
        }
        directory = flight_dir()
        with self._lock:
            self._seq += 1
            seq = self._seq
        # millis alone can collide under rapid dumps — the sequence
        # number keeps names unique (and lexically chronological: the
        # prune relies on sort order)
        name = (
            f"flight-{int(time.time() * 1000):013d}-{seq:06d}-"
            f"{_slug(reason)}.json"
        )
        path = os.path.join(directory, name)
        try:
            # write-then-rename: a dump must appear ATOMICALLY — dump
            # consumers (and the tests) poll the directory and read as
            # soon as the name shows up, so an in-progress write must
            # not be observable as an empty/truncated JSON file. The
            # temp name is opaque (no reason slug, hidden) so no
            # directory poll can match it mid-write.
            tmp_path = os.path.join(directory, f".flight-{seq:06d}.tmp")
            with open(tmp_path, "w", encoding="utf-8") as fh:
                # default=repr: one unserializable leaf must not lose
                # the dump
                json.dump(payload, fh, indent=1, default=repr)
            os.replace(tmp_path, path)
        except BaseException:
            try:
                # a failed write must not strand its temp file in the
                # operator's dump directory (crash-looping full disks)
                os.unlink(tmp_path)
            except OSError:
                pass
            with self._lock:
                # roll back the reservation: nothing was captured, so
                # the next attempt must not be rate-limited away
                if self._last_dump.get(reason) == now:
                    if last is None:
                        self._last_dump.pop(reason, None)
                    else:
                        self._last_dump[reason] = last
            raise
        _prune(directory)
        bus.incr("flightrecorder_dumps_total", reason=reason)
        bus.record("flightrecorder.dump", reason=reason, path=path)
        return path

    def dump_soon(
        self,
        reason: str,
        snapshot: Any = None,
        error: BaseException | str | None = None,
    ) -> None:
        """Fire-and-forget dump on a short-lived thread — the handler
        dispatch path must not pay file I/O inline. The rate-limit check
        runs inside ``dump``; an exception storm spawns at most one
        writer per interval's worth of no-op threads."""
        if not self.should_dump(reason):
            return
        snapshot = redact(snapshot)  # capture caller state NOW, not later

        def _write() -> None:
            try:
                self.dump(reason, snapshot, error, snapshot_redacted=True)
            except Exception:  # noqa: BLE001 — capture is best-effort
                logging.getLogger(__name__).exception(
                    "flight-recorder capture failed"
                )

        threading.Thread(
            target=_write, name="pygrid-flight-dump", daemon=True
        ).start()

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._last_dump.clear()
            self._providers.clear()


#: default seconds between periodic engine snapshots (env-overridable)
DEFAULT_SNAPSHOT_INTERVAL_S = 10.0


class PeriodicSnapshotter:
    """Low-cadence engine snapshots onto the flight-recorder ring
    (docs/OBSERVABILITY.md §7): every ~10 s *under load*, one
    ``engine.snapshot`` note carrying every registered stats provider's
    live numbers — so a crash dump shows the trajectory BEFORE the
    crash (queue depths climbing, a fold stalling), not just the final
    frame. "Under load" is a counter-delta gate: an idle process writes
    nothing, keeping the ring for real moments and the cost at zero.

    Refcounted like the profiler's memory sampler: several apps in one
    process (the test grid) share the thread; it stops with the last
    ``stop()``."""

    def __init__(
        self, recorder: "FlightRecorder", interval_s: float | None = None
    ) -> None:
        self._recorder = recorder
        self._interval_override = interval_s
        self._stop_event = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._starts = 0
        self._last_counters: dict | None = None
        self.snapshots = 0  # taken (post-gate), for tests/stats

    def _interval(self) -> float:
        if self._interval_override is not None:
            return self._interval_override
        return max(
            0.05,
            bus.env_float(
                "PYGRID_FLIGHT_SNAPSHOT_S", DEFAULT_SNAPSHOT_INTERVAL_S
            ),
        )

    def start(self) -> None:
        with self._lock:
            self._starts += 1
            if self._thread is not None:
                return
            self._stop_event.clear()
            self._thread = threading.Thread(
                target=self._run, name="pygrid-flight-snapshot", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            self._starts = max(0, self._starts - 1)
            if self._starts > 0 or self._thread is None:
                return
            thread = self._thread
            self._thread = None
        self._stop_event.set()
        thread.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop_event.wait(self._interval()):
            try:
                self.snapshot_once()
            except Exception:  # noqa: BLE001 — cadence must survive
                logging.getLogger(__name__).exception(
                    "periodic engine snapshot failed"
                )

    def snapshot_once(self, force: bool = False) -> bool:
        """One gated snapshot; returns whether a note was written.
        ``force`` skips the activity gate (tests, operator paths)."""
        if not enabled():
            return False
        counters = dict(bus.counters())
        if not force and counters == self._last_counters:
            return False  # idle since the last tick — nothing to record
        self._recorder.note(
            "engine.snapshot",
            stats=redact(self._recorder._provider_stats()),
        )
        self.snapshots += 1
        bus.incr("flightrecorder_snapshots_total")
        # the gate's baseline is the POST-snapshot counter state — the
        # snapshot's own counter must not read as "activity" next tick
        self._last_counters = dict(bus.counters())
        return True


def _slug(reason: str) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "-" for c in reason)[:48]


def _counter_label(name: str, labels: tuple) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in labels)
    return f"{name}{{{inner}}}"


def _prune(directory: str) -> None:
    """Keep the newest :data:`MAX_DUMPS` files PER REASON: a flood of
    one reason (an operator scripting ``POST /telemetry/dump``, an
    exception storm) must not evict another reason's crash evidence —
    reasons are code-bounded, so the total stays bounded too."""
    try:
        by_reason: dict[str, list[str]] = {}
        for f in sorted(os.listdir(directory)):
            if f.startswith("flight-") and f.endswith(".json"):
                # filename shape: flight-<millis>-<seq>-<reason>.json
                slug = f[len("flight-"):-len(".json")].split("-", 2)[-1]
                by_reason.setdefault(slug, []).append(f)
        for dumps in by_reason.values():
            for stale in dumps[:-MAX_DUMPS]:
                os.unlink(os.path.join(directory, stale))
    except OSError:  # pruning is best-effort; the dump already landed
        pass


#: the process-wide recorder — module functions are its bound methods
RECORDER = FlightRecorder()
#: its periodic-snapshot driver (started by app lifecycles, refcounted)
SNAPSHOTTER = PeriodicSnapshotter(RECORDER)

note = RECORDER.note
dump = RECORDER.dump
dump_soon = RECORDER.dump_soon
should_dump = RECORDER.should_dump
ring = RECORDER.ring
register_stats_provider = RECORDER.register_stats_provider
reset = RECORDER.reset
start_snapshots = SNAPSHOTTER.start
stop_snapshots = SNAPSHOTTER.stop
