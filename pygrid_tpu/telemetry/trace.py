"""Distributed-trace context and its wire encodings.

One FL cycle touches four processes (client → node ws → cycle manager →
back), and the point of tracing is that every span they record carries
the SAME ``trace_id`` so the round stitches into one timeline. The
context rides three wire shapes:

- **wire-v2 binary frames**: a 24-byte header (16-byte trace id +
  8-byte span id) between the frame tag byte and the payload, flagged
  by the tag's high bit (``serde.wire.FRAME_TRACE_FLAG``);
- **legacy JSON framing**: a ``trace`` field on the message envelope,
  compact text form ``"<32 hex trace_id>-<16 hex span_id>"``;
- **HTTP**: the ``X-PyGrid-Trace`` request header, same text form.

A server receiving no trace context **synthesizes a root trace** — a
legacy client's cycle is still fully traced node-side, it just cannot
contribute client spans.

Context lives in a :mod:`contextvars` variable, so it propagates through
``await`` and stays isolated between the node's executor threads.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import re
import time
from typing import Iterator, NamedTuple

from pygrid_tpu.telemetry import bus

#: HTTP request header carrying the compact text form
TRACE_HEADER = "X-PyGrid-Trace"

_HEADER_RE = re.compile(r"^([0-9a-f]{32})-([0-9a-f]{16})$")


class TraceContext(NamedTuple):
    trace_id: str  # 32 lowercase hex chars (16 bytes)
    span_id: str   # 16 lowercase hex chars (8 bytes)


_current: contextvars.ContextVar[TraceContext | None] = contextvars.ContextVar(
    "pygrid_trace", default=None
)


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def current() -> TraceContext | None:
    return _current.get()


# ── wire encodings ──────────────────────────────────────────────────────


def header(ctx: TraceContext | None = None) -> str | None:
    """Compact text form for JSON fields / HTTP headers."""
    ctx = ctx or _current.get()
    if ctx is None:
        return None
    return f"{ctx.trace_id}-{ctx.span_id}"


def parse_header(value: object) -> TraceContext | None:
    """Strict parse of the compact text form; anything malformed (wrong
    length, non-hex, wrong type) is None — peer-supplied bytes must not
    raise out of the framing layer."""
    if not isinstance(value, str):
        return None
    m = _HEADER_RE.match(value)
    if m is None:
        return None
    return TraceContext(m.group(1), m.group(2))


def to_bytes(ctx: TraceContext | None = None) -> bytes | None:
    """The 24-byte wire-v2 frame header form."""
    ctx = ctx or _current.get()
    if ctx is None:
        return None
    return bytes.fromhex(ctx.trace_id) + bytes.fromhex(ctx.span_id)


def from_bytes(raw: bytes | bytearray | memoryview | None) -> TraceContext | None:
    if raw is None:
        return None
    raw = bytes(raw)
    if len(raw) != 24:
        return None
    return TraceContext(raw[:16].hex(), raw[16:].hex())


# ── context management ──────────────────────────────────────────────────


@contextlib.contextmanager
def use(ctx: TraceContext) -> Iterator[TraceContext]:
    """Activate an explicit context (e.g. an FLJob's cycle-long root) for
    the duration of the block."""
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


@contextlib.contextmanager
def serve(incoming: TraceContext | None = None) -> Iterator[TraceContext]:
    """Server-side adoption: a child span of ``incoming`` when the peer
    sent context, a child of the already-active context when nested, and
    a fresh synthesized root otherwise (the legacy-client path)."""
    parent = incoming if incoming is not None else _current.get()
    ctx = TraceContext(
        parent.trace_id if parent is not None else new_trace_id(),
        new_span_id(),
    )
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


@contextlib.contextmanager
def span(name: str, **fields: object) -> Iterator[TraceContext]:
    """Record a named span: activates a child context for the block and
    appends one ``span`` event (trace/span/parent ids + duration) to the
    bus at exit."""
    parent = _current.get()
    ctx = TraceContext(
        parent.trace_id if parent is not None else new_trace_id(),
        new_span_id(),
    )
    token = _current.set(ctx)
    t0 = time.monotonic()
    try:
        yield ctx
    finally:
        _current.reset(token)
        bus.record(
            "span",
            name=name,
            trace_id=ctx.trace_id,
            span_id=ctx.span_id,
            parent_id=parent.span_id if parent is not None else None,
            duration_s=time.monotonic() - t0,
            **fields,
        )
