"""Grid telemetry — the shared instrumentation spine.

The reference stack has no observability at all (stdlib logging only —
SURVEY §5.1, §5.5). This subsystem is what a production grid operates
through:

- :mod:`pygrid_tpu.telemetry.bus` — a process-wide, always-on,
  lock-cheap event bus: ring-buffered structured events, labeled
  counters, and log-linear-bucket histograms.
- :mod:`pygrid_tpu.telemetry.trace` — distributed-trace context
  (``trace_id``/``span_id``) with the wire encodings: a 24-byte header
  on wire-v2 binary frames, a ``trace`` JSON field on legacy framing,
  and ``X-PyGrid-Trace`` on HTTP.
- :mod:`pygrid_tpu.telemetry.timeline` — per-FL-cycle round timelines
  (phase durations, per-worker report latency, bytes per codec,
  straggler counts), served by ``GET /telemetry/cycles/<id>``.
- :mod:`pygrid_tpu.telemetry.promtext` — a strict Prometheus
  text-format parser used by the scrape-validity tests (and handy for
  ops tooling).
- :mod:`pygrid_tpu.telemetry.profiler` — per-jit-callsite
  compile/execute timing (``GET /telemetry/programs``) and background
  device-memory gauges; off-switch ``PYGRID_PROFILER=off``.
- :mod:`pygrid_tpu.telemetry.recorder` — the flight recorder: a
  bounded ring of notable moments and redacted JSON crash dumps on
  engine failure / unhandled handler exceptions / operator request.
- :mod:`pygrid_tpu.telemetry.slo` — declarative burn-rate SLOs over
  the bus histograms (``GET /telemetry/slo``, the deep ``/healthz``).

Everything here must stay cheap enough to be ON by default: the hot
loop's budget is < 2% over the bare wire path
(``bench.bench_telemetry_overhead``).
"""

from __future__ import annotations

from pygrid_tpu.telemetry import (  # noqa: F401
    profiler,
    recorder,
    slo,
    timeline,
    trace,
)
from pygrid_tpu.telemetry.bus import (  # noqa: F401
    BUS,
    Histogram,
    counters,
    events,
    histograms,
    incr,
    observe,
    record,
    reset,
)
from pygrid_tpu.telemetry.trace import (  # noqa: F401
    TRACE_HEADER,
    TraceContext,
    current,
    span,
)


def export(exp) -> None:
    """Write every bus counter and histogram family into an
    :class:`pygrid_tpu.utils.metrics.Exposition` — the one exporter both
    the node and network ``/metrics`` routes call, so the exposed
    families cannot drift between the two apps."""
    from pygrid_tpu.serde import tensor_copy_count
    from pygrid_tpu.telemetry.bus import family_help

    for (name, labels), value in sorted(counters().items()):
        exp.counter(name, value, family_help(name), dict(labels))
    for (name, labels), snap in sorted(histograms().items()):
        exp.histogram(name, snap, family_help(name), dict(labels))
    exp.counter(
        "serde_tensor_copies_total",
        tensor_copy_count(),
        "tensor-buffer byte copies made by wire deserialization",
    )


def http_middleware():
    """aiohttp middleware shared by the node and network apps: adopts the
    ``X-PyGrid-Trace`` header (or synthesizes a root trace for legacy
    clients), and feeds the per-route request-latency histogram and
    status-code counter. WebSocket upgrades are counted but not timed —
    a connection's lifetime is not a request latency."""
    import time

    from aiohttp import web

    @web.middleware
    async def middleware(request, handler):
        incoming = trace.parse_header(
            request.headers.get(TRACE_HEADER, "")
        )
        route = "unmatched"
        resource = request.match_info.route.resource
        if resource is not None:
            route = resource.canonical
        t0 = time.monotonic()
        status = 500
        websocket = False
        with trace.serve(incoming):
            try:
                try:
                    response = await handler(request)
                except web.HTTPException as err:
                    # aiohttp signals router 404/405 (and handler
                    # redirects) by raising — that's the status the
                    # client sees, not a 500
                    status = err.status
                    raise
                status = response.status
                websocket = isinstance(response, web.WebSocketResponse)
                return response
            finally:
                incr(
                    "http_requests_total",
                    1,
                    route=route,
                    code=str(status),
                )
                if not websocket:
                    observe(
                        "http_request_seconds",
                        time.monotonic() - t0,
                        route=route,
                    )

    return middleware
