"""Per-FL-cycle round timelines.

Answers "where did this cycle's 8 seconds go?" in one place: per-phase
durations, per-worker report latency, wire bytes per codec, straggler
counts, and the trace ids that stitch the cycle to client spans. The
node's ``CycleManager`` feeds these hooks at assign/report/aggregate
time; ``GET /telemetry/cycles/<id>`` serves the snapshot (merged with
the durable worker rows from SQL).

In-memory and bounded (the durable record is the worker-cycle table):
the registry keeps the most recent :data:`MAX_CYCLES` cycles and evicts
oldest-first. All hooks are no-fail — a telemetry bug must never break
a cycle — and cheap enough for the per-report path.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any

#: cycles kept in memory (oldest evicted first)
MAX_CYCLES = 256

_lock = threading.Lock()
_cycles: "OrderedDict[int, dict]" = OrderedDict()


def _fresh_entry(cycle_id: int) -> dict:
    return {
        "cycle_id": cycle_id,
        "fl_process_id": None,
        "sequence": None,
        "created_ts": time.time(),
        "completed_ts": None,
        "phases": {},          # phase name -> cumulative seconds
        "workers": {},         # worker_id -> report record
        "bytes": {},           # "direction/codec" -> bytes
        "traces": [],          # trace ids seen for this cycle
        "assigned": 0,
        "reported": 0,
        "stragglers": None,
        "outcome": None,
    }


def _get_or_create(cycle_id: int) -> dict:
    """Caller holds ``_lock``."""
    entry = _cycles.get(cycle_id)
    if entry is None:
        entry = _cycles[cycle_id] = _fresh_entry(cycle_id)
        while len(_cycles) > MAX_CYCLES:
            _cycles.popitem(last=False)
    return entry


def cycle_started(
    cycle_id: int,
    fl_process_id: int | None = None,
    sequence: int | None = None,
) -> None:
    with _lock:
        # a NEW cycle under an already-seen id (fresh DB after a restart,
        # or the in-process test grid re-numbering from 1) replaces the
        # stale record outright — and re-enters the eviction order at the
        # back, so `recent()` reflects creation recency, not first-ever
        # sighting of the id
        _cycles.pop(cycle_id, None)
        entry = _get_or_create(cycle_id)
        entry["fl_process_id"] = fl_process_id
        entry["sequence"] = sequence


def worker_assigned(
    cycle_id: int, worker_id: str, trace_id: str | None = None
) -> None:
    with _lock:
        entry = _get_or_create(cycle_id)
        entry["assigned"] += 1
        entry["workers"].setdefault(
            worker_id, {"assigned_ts": time.time()}
        )
        _note_trace(entry, trace_id)


def worker_report(
    cycle_id: int,
    worker_id: str,
    latency_s: float | None = None,
    n_bytes: int = 0,
    codec: str | None = None,
    trace_id: str | None = None,
) -> None:
    with _lock:
        entry = _get_or_create(cycle_id)
        entry["reported"] += 1
        rec = entry["workers"].setdefault(worker_id, {})
        rec.update(
            {
                "report_latency_s": latency_s,
                "report_bytes": n_bytes,
                "codec": codec,
                "trace_id": trace_id,
                "reported_ts": time.time(),
            }
        )
        _note_trace(entry, trace_id)
        _add_bytes(entry, "upload", codec, n_bytes)


def add_bytes(
    cycle_id: int, direction: str, codec: str | None, n_bytes: int
) -> None:
    with _lock:
        _add_bytes(_get_or_create(cycle_id), direction, codec, n_bytes)


def phase(cycle_id: int, name: str, seconds: float) -> None:
    with _lock:
        phases = _get_or_create(cycle_id)["phases"]
        phases[name] = phases.get(name, 0.0) + float(seconds)


def cycle_closed(
    cycle_id: int,
    assigned: int | None = None,
    reported: int | None = None,
    outcome: str = "aggregated",
) -> None:
    with _lock:
        entry = _get_or_create(cycle_id)
        entry["completed_ts"] = time.time()
        entry["outcome"] = outcome
        if assigned is not None:
            entry["assigned"] = assigned
        if reported is not None:
            entry["reported"] = reported
        entry["stragglers"] = max(
            0, entry["assigned"] - entry["reported"]
        )


def snapshot(cycle_id: int) -> dict | None:
    """Deep-enough copy for a JSON response; None when unknown (evicted
    or never observed — the route then falls back to SQL alone)."""
    with _lock:
        entry = _cycles.get(cycle_id)
        if entry is None:
            return None
        out = dict(entry)
        out["phases"] = dict(entry["phases"])
        out["workers"] = {k: dict(v) for k, v in entry["workers"].items()}
        out["bytes"] = dict(entry["bytes"])
        out["traces"] = list(entry["traces"])
        return out


def recent(limit: int = 20) -> list[dict]:
    """Newest-first summaries for the listing route / dashboard."""
    with _lock:
        ids = list(_cycles.keys())[-limit:][::-1]
    out = []
    for cid in ids:
        snap = snapshot(cid)
        if snap is None:
            continue
        out.append(
            {
                k: snap[k]
                for k in (
                    "cycle_id", "fl_process_id", "sequence", "created_ts",
                    "completed_ts", "assigned", "reported", "stragglers",
                    "outcome", "phases",
                )
            }
        )
    return out


def reset() -> None:
    with _lock:
        _cycles.clear()


def _note_trace(entry: dict, trace_id: str | None) -> None:
    if trace_id and trace_id not in entry["traces"]:
        entry["traces"].append(trace_id)


def _add_bytes(
    entry: dict, direction: str, codec: str | None, n_bytes: int
) -> None:
    if n_bytes:
        key = f"{direction}/{codec or 'raw'}"
        entry["bytes"][key] = entry["bytes"].get(key, 0) + int(n_bytes)


def merge_db_workers(snap: dict, rows: list[Any]) -> dict:
    """Fold the durable worker-cycle rows into a snapshot: the in-memory
    record has wire detail (bytes/codec/trace) for reports this process
    saw; the SQL rows are authoritative for who was assigned and when —
    a restarted node still serves a useful timeline."""
    workers = snap.setdefault("workers", {})
    for row in rows:
        rec = workers.setdefault(row.worker_id, {})
        if getattr(row, "started_at", None) is not None:
            rec.setdefault("assigned_at", row.started_at.isoformat())
        completed_at = getattr(row, "completed_at", None)
        if completed_at is not None:
            rec.setdefault("reported_at", completed_at.isoformat())
            started_at = getattr(row, "started_at", None)
            if started_at is not None:
                rec.setdefault(
                    "report_latency_s",
                    (completed_at - started_at).total_seconds(),
                )
    return snap
