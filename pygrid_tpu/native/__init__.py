"""Native host-side kernels with transparent numpy fallbacks.

Parity surface: the reference's native serving hot spots — wsaccel's C
websocket masking (``apps/node/pyproject.toml:31``) and the numpy XOR
masking patch it applies over geventwebsocket
(``apps/node/src/app/util.py:5-24``, installed at
``app/__init__.py:19-21``) — plus the protobuf C++ tensor payload packing.
TPU-native additions: float32↔bfloat16 wire conversion (round-to-nearest-
even, matching XLA) so FL diffs/checkpoints can travel at half width.

Every entry point works without the compiled library (numpy / ml_dtypes
fallbacks); ``BACKEND`` says which implementation is live."""

from __future__ import annotations

import ctypes
from typing import Any

import numpy as np

from pygrid_tpu.native.build import ensure_built

__all__ = [
    "BACKEND",
    "xor_mask",
    "f32_to_bf16",
    "bf16_to_f32",
    "install_ws_masking",
]

_lib: Any = None
BACKEND = "numpy"


def _load() -> None:
    global _lib, BACKEND
    path = ensure_built()
    if path is None:
        return
    try:
        lib = ctypes.CDLL(str(path))
        lib.pg_xor_mask.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p
        ]
        lib.pg_f32_to_bf16.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64
        ]
        lib.pg_bf16_to_f32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64
        ]
        lib.pg_abi_version.restype = ctypes.c_int
        if lib.pg_abi_version() == 1:
            _lib = lib
            BACKEND = "native"
    except OSError:
        pass


_load()


def xor_mask(data: bytes | bytearray, mask: bytes) -> bytearray:
    """Websocket frame (un)masking: ``data ^ cycle(mask4)``."""
    if len(mask) != 4:
        raise ValueError("mask must be 4 bytes")
    out = bytearray(data)
    if _lib is not None:
        buf = (ctypes.c_char * len(out)).from_buffer(out)
        _lib.pg_xor_mask(buf, len(out), mask)
        return out
    arr = np.frombuffer(out, dtype=np.uint8)
    pattern = np.frombuffer(
        (mask * (len(out) // 4 + 1))[: len(out)], dtype=np.uint8
    )
    np.bitwise_xor(arr, pattern, out=arr)
    return out


def f32_to_bf16(arr: np.ndarray) -> np.ndarray:
    """float32 → bfloat16 bit pattern (uint16), round-to-nearest-even."""
    src = np.ascontiguousarray(arr, dtype=np.float32)
    out = np.empty(src.shape, dtype=np.uint16)
    if _lib is not None and src.size:
        _lib.pg_f32_to_bf16(
            src.ctypes.data, out.ctypes.data, src.size
        )
        return out
    import ml_dtypes

    return src.astype(ml_dtypes.bfloat16).view(np.uint16)


def bf16_to_f32(arr: np.ndarray) -> np.ndarray:
    """bfloat16 bit pattern (uint16) → float32 (exact)."""
    src = np.ascontiguousarray(arr, dtype=np.uint16)
    out = np.empty(src.shape, dtype=np.float32)
    if _lib is not None and src.size:
        _lib.pg_bf16_to_f32(
            src.ctypes.data, out.ctypes.data, src.size
        )
        return out
    import ml_dtypes

    return src.view(ml_dtypes.bfloat16).astype(np.float32)


def install_ws_masking() -> bool:
    """Patch ``websockets``' pure-python ``apply_mask`` with the native one.

    Direct analog of the reference's masking patch (util.py:5-24). No-op
    when the library already has its C speedups or we only have numpy."""
    if _lib is None:
        return False
    try:
        from websockets import frames, utils
    except ImportError:
        return False
    # the C accelerator (when installed) is bound at frames.apply_mask with
    # __module__ "websocket.speedups" — leave it alone, it's already native
    if "speedup" in getattr(frames.apply_mask, "__module__", ""):
        return False

    def native_apply_mask(data: bytes, mask: bytes) -> bytes:
        return bytes(xor_mask(data, mask))

    utils.apply_mask = native_apply_mask
    frames.apply_mask = native_apply_mask
    return True
