"""Native host-side kernels with transparent numpy fallbacks.

Parity surface: the reference's native serving hot spots — wsaccel's C
websocket masking (``apps/node/pyproject.toml:31``) and the numpy XOR
masking patch it applies over geventwebsocket
(``apps/node/src/app/util.py:5-24``, installed at
``app/__init__.py:19-21``) — plus the protobuf C++ tensor payload packing.
TPU-native additions: float32↔bfloat16 wire conversion (round-to-nearest-
even, matching XLA) so FL diffs/checkpoints can travel at half width.

Every entry point works without the compiled library (numpy / ml_dtypes
fallbacks); ``BACKEND`` says which implementation is live."""

from __future__ import annotations

import ctypes
from typing import Any

import numpy as np

from pygrid_tpu.native.build import ensure_built

__all__ = [
    "BACKEND",
    "xor_mask",
    "xor_mask_inplace",
    "b64_decode",
    "b64_decode_view",
    "f32_to_bf16",
    "bf16_to_f32",
    "accum_f32",
    "accum_bf16",
    "install_ws_masking",
]

_lib: Any = None
BACKEND = "numpy"


def _load() -> None:
    global _lib, BACKEND
    path = ensure_built()
    if path is None:
        return
    try:
        lib = ctypes.CDLL(str(path))
        lib.pg_xor_mask.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p
        ]
        lib.pg_f32_to_bf16.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64
        ]
        lib.pg_bf16_to_f32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64
        ]
        lib.pg_accum_f32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_double, ctypes.c_uint64
        ]
        lib.pg_accum_bf16.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_double, ctypes.c_uint64
        ]
        lib.pg_b64_decode.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_void_p
        ]
        lib.pg_b64_decode.restype = ctypes.c_int64
        lib.pg_abi_version.restype = ctypes.c_int
        if lib.pg_abi_version() == 2:
            _lib = lib
            BACKEND = "native"
    except (OSError, AttributeError):
        # AttributeError: a stale cached .so predating the current ABI is
        # missing newer symbols — fall back to numpy, don't break import
        pass


_load()


def xor_mask(data: bytes | bytearray, mask: bytes) -> bytearray:
    """Websocket frame (un)masking: ``data ^ cycle(mask4)``."""
    if len(mask) != 4:
        raise ValueError("mask must be 4 bytes")
    out = bytearray(data)
    if _lib is not None:
        buf = (ctypes.c_char * len(out)).from_buffer(out)
        _lib.pg_xor_mask(buf, len(out), mask)
        return out
    arr = np.frombuffer(out, dtype=np.uint8)
    pattern = np.frombuffer(
        (mask * (len(out) // 4 + 1))[: len(out)], dtype=np.uint8
    )
    np.bitwise_xor(arr, pattern, out=arr)
    return out


def xor_mask_inplace(
    buf: bytearray, mask: bytes, offset: int = 0
) -> None:
    """Mask ``buf[offset:]`` in place — the zero-extra-copy framing path
    (the caller already assembled the frame buffer)."""
    n = len(buf) - offset
    if n <= 0:
        return
    if _lib is not None:
        view = (ctypes.c_char * n).from_buffer(buf, offset)
        _lib.pg_xor_mask(view, n, mask)
        return
    arr = np.frombuffer(buf, dtype=np.uint8, offset=offset)
    pattern = np.frombuffer((mask * (n // 4 + 1))[:n], dtype=np.uint8)
    np.bitwise_xor(arr, pattern, out=arr)


def f32_to_bf16(arr: np.ndarray) -> np.ndarray:
    """float32 → bfloat16 bit pattern (uint16), round-to-nearest-even."""
    src = np.ascontiguousarray(arr, dtype=np.float32)
    if src.ctypes.data % src.itemsize:
        # zero-copy wire views sit at arbitrary byte offsets and
        # ascontiguousarray does NOT realign — same hazard as bf16_to_f32
        src = src.copy()
    out = np.empty(src.shape, dtype=np.uint16)
    if _lib is not None and src.size:
        _lib.pg_f32_to_bf16(
            src.ctypes.data, out.ctypes.data, src.size
        )
        return out
    import ml_dtypes

    return src.astype(ml_dtypes.bfloat16).view(np.uint16)


def bf16_to_f32(arr: np.ndarray) -> np.ndarray:
    """bfloat16 bit pattern (uint16) → float32 (exact)."""
    src = np.ascontiguousarray(arr, dtype=np.uint16)
    if src.ctypes.data % src.itemsize:
        # wire views can sit at any byte offset and ascontiguousarray does
        # NOT realign — same unaligned-pointer hazard as accum_f32
        src = src.copy()
    out = np.empty(src.shape, dtype=np.float32)
    if _lib is not None and src.size:
        _lib.pg_bf16_to_f32(
            src.ctypes.data, out.ctypes.data, src.size
        )
        return out
    import ml_dtypes

    return src.view(ml_dtypes.bfloat16).astype(np.float32)


def b64_decode(data: str | bytes) -> bytes:
    """Standard-alphabet base64 decode (padding required, no whitespace),
    ~3× CPython's ``binascii`` on megabyte payloads. Raises ``ValueError``
    on malformed input."""
    return bytes(b64_decode_view(data))


def b64_decode_view(data: str | bytes) -> memoryview | bytes:
    """Like :func:`b64_decode` but returns a memoryview over a freshly
    decoded buffer — no final copy. The FL report ingest decodes ~1.7 MB
    per report; every pass skipped is protocol throughput."""
    raw = data.encode("ascii") if isinstance(data, str) else data
    if _lib is None:
        import base64 as _b64

        return _b64.b64decode(raw, validate=True)
    if len(raw) % 4:
        raise ValueError("invalid base64 payload")
    pad = 0
    if raw[-1:] == b"=":
        pad = 2 if raw[-2:] == b"==" else 1
    n_out = 3 * (len(raw) // 4) - pad
    out = np.empty(max(n_out, 1), dtype=np.uint8)  # no memset, no resize
    n = _lib.pg_b64_decode(
        raw if isinstance(raw, bytes) else bytes(raw),
        len(raw), out.ctypes.data,
    )
    if n != n_out:
        raise ValueError("invalid base64 payload")
    return memoryview(out.data)[:n_out].cast("B") if n_out else b""


def accum_f32(acc: np.ndarray, src, weight: float = 1.0) -> None:
    """``acc += weight * src`` in one pass, float64 carry, no temporaries.

    ``acc`` is a C-contiguous float64 array; ``src`` is a float32 array or
    any buffer of ``acc.size`` float32 values (e.g. a memoryview straight
    out of the wire decoder — the FL report fold never copies)."""
    if not isinstance(src, np.ndarray):
        src = np.frombuffer(src, dtype=np.float32)
    if src.size != acc.size:
        raise ValueError(f"accum_f32 size mismatch: {src.size} != {acc.size}")
    if _lib is not None and acc.size:
        src = np.ascontiguousarray(src, dtype=np.float32)
        # np.frombuffer over a msgpack blob can sit at any byte offset
        # and ascontiguousarray does NOT realign — dereferencing an
        # unaligned const float* is UB in C (works on x86-64, can trap
        # on stricter targets)
        if src.ctypes.data % src.itemsize:
            src = src.copy()
        _lib.pg_accum_f32(
            acc.ctypes.data, src.ctypes.data, float(weight), acc.size
        )
        return
    flat = acc.reshape(-1)
    if weight == 1.0:
        np.add(flat, src.reshape(-1), out=flat)
    else:
        flat += np.multiply(src.reshape(-1), weight, dtype=np.float64)


def accum_bf16(acc: np.ndarray, src, weight: float = 1.0) -> None:
    """``acc += weight * decode_bf16(src)`` fused in one pass — the bf16
    wire report accumulates without ever materializing as float32."""
    if not isinstance(src, np.ndarray):
        src = np.frombuffer(src, dtype=np.uint16)
    if src.size != acc.size:
        raise ValueError(f"accum_bf16 size mismatch: {src.size} != {acc.size}")
    if _lib is not None and acc.size:
        src = np.ascontiguousarray(src, dtype=np.uint16)
        # same unaligned-wire-offset hazard as accum_f32 above
        if src.ctypes.data % src.itemsize:
            src = src.copy()
        _lib.pg_accum_bf16(
            acc.ctypes.data, src.ctypes.data, float(weight), acc.size
        )
        return
    accum_f32(acc, bf16_to_f32(src), weight)


def install_ws_masking() -> bool:
    """Patch ``websockets``' pure-python ``apply_mask`` with the native one.

    Direct analog of the reference's masking patch (util.py:5-24). No-op
    when the library already has its C speedups or we only have numpy."""
    if _lib is None:
        return False
    try:
        from websockets import frames, utils
    except ImportError:
        return False
    # the C accelerator (when installed) is bound at frames.apply_mask with
    # __module__ "websocket.speedups" — leave it alone, it's already native
    if "speedup" in getattr(frames.apply_mask, "__module__", ""):
        return False

    def native_apply_mask(data: bytes, mask: bytes) -> bytes:
        return bytes(xor_mask(data, mask))

    utils.apply_mask = native_apply_mask
    frames.apply_mask = native_apply_mask
    return True
