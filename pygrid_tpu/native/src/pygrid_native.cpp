// Native runtime kernels for the host-side hot paths.
//
// Parity rationale: the reference's serving plane leans on native code for
// exactly these spots — wsaccel (C websocket masking, apps/node/
// pyproject.toml:31) plus a numpy XOR patch (apps/node/src/app/util.py:5-24),
// and protobuf's C++ for tensor payload packing. Here the equivalents are a
// word-wide XOR mask and float32<->bfloat16 wire conversion (TPU-native
// payload dtype), exported with a plain C ABI for ctypes.
//
// Build: pygrid_tpu/native/build.py shells out to g++ -O3 -shared -fPIC.

#include <cstddef>
#include <cstdint>
#include <cstring>

extern "C" {

// XOR-mask `n` bytes of `buf` in place with the 4-byte websocket mask.
// Word-wide main loop (the per-byte tail is at most 7 iterations); with -O3
// the 64-bit loop auto-vectorizes.
void pg_xor_mask(uint8_t *buf, uint64_t n, const uint8_t mask[4]) {
    uint64_t wide;
    uint8_t rep[8];
    for (int i = 0; i < 8; ++i) rep[i] = mask[i & 3];
    std::memcpy(&wide, rep, 8);

    uint64_t i = 0;
    // align to 8 so the wide loop reads aligned words
    for (; i < n && (reinterpret_cast<uintptr_t>(buf + i) & 7); ++i)
        buf[i] ^= mask[i & 3];
    // the mask phase at offset i: rotate the replicated word to match
    uint64_t phase = i & 3;
    uint64_t m = wide;
    if (phase) {
        uint8_t rot[8];
        for (int k = 0; k < 8; ++k) rot[k] = rep[(k + phase) & 3];
        std::memcpy(&m, rot, 8);
    }
    for (; i + 8 <= n; i += 8) {
        uint64_t w;
        std::memcpy(&w, buf + i, 8);
        w ^= m;
        std::memcpy(buf + i, &w, 8);
    }
    for (; i < n; ++i) buf[i] ^= mask[i & 3];
}

// float32 -> bfloat16 with round-to-nearest-even (matches XLA/ml_dtypes).
// NaNs are quieted to 0x7fc0-style payloads by the +rounding carry being
// suppressed: standard trick — if NaN, emit the truncated bits with the
// quiet bit forced.
void pg_f32_to_bf16(const uint32_t *src, uint16_t *dst, uint64_t n) {
    for (uint64_t i = 0; i < n; ++i) {
        uint32_t x = src[i];
        uint32_t exp = x & 0x7f800000u;
        if (exp == 0x7f800000u && (x & 0x007fffffu)) {
            dst[i] = static_cast<uint16_t>((x >> 16) | 0x0040u);  // quiet NaN
        } else {
            uint32_t rounding = 0x7fffu + ((x >> 16) & 1u);
            dst[i] = static_cast<uint16_t>((x + rounding) >> 16);
        }
    }
}

// bfloat16 -> float32 (exact: left shift).
void pg_bf16_to_f32(const uint16_t *src, uint32_t *dst, uint64_t n) {
    for (uint64_t i = 0; i < n; ++i)
        dst[i] = static_cast<uint32_t>(src[i]) << 16;
}

// Weighted accumulate: acc[i] += w * src[i] with a float64 carry — the
// FL report-ingest fold. One pass, no temporaries (the Python-side numpy
// fold allocated a full f64 copy of every diff tensor per report).
void pg_accum_f32(double *acc, const float *src, double w, uint64_t n) {
    if (w == 1.0) {
        for (uint64_t i = 0; i < n; ++i) acc[i] += static_cast<double>(src[i]);
    } else {
        for (uint64_t i = 0; i < n; ++i)
            acc[i] += w * static_cast<double>(src[i]);
    }
}

// Same fold fused with the bf16 wire decode: bf16 bit patterns accumulate
// straight into the float64 carry — the report never materializes as f32.
void pg_accum_bf16(double *acc, const uint16_t *src, double w, uint64_t n) {
    if (w == 1.0) {
        for (uint64_t i = 0; i < n; ++i) {
            uint32_t bits = static_cast<uint32_t>(src[i]) << 16;
            float f;
            std::memcpy(&f, &bits, 4);
            acc[i] += static_cast<double>(f);
        }
    } else {
        for (uint64_t i = 0; i < n; ++i) {
            uint32_t bits = static_cast<uint32_t>(src[i]) << 16;
            float f;
            std::memcpy(&f, &bits, 4);
            acc[i] += w * static_cast<double>(f);
        }
    }
}

// Base64 decode (standard alphabet, '=' padding, no whitespace). Returns
// the decoded byte count, or -1 on any invalid character / bad padding.
// One table-driven pass — the FL report path decodes ~1.7 MB per report
// and CPython's binascii adds a str→bytes transcode on top.
static const int8_t B64_REV[256] = {
    // generated: -1 everywhere except A-Z a-z 0-9 + /
    -1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,
    -1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,
    -1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,62,-1,-1,-1,63,
    52,53,54,55,56,57,58,59,60,61,-1,-1,-1,-1,-1,-1,
    -1, 0, 1, 2, 3, 4, 5, 6, 7, 8, 9,10,11,12,13,14,
    15,16,17,18,19,20,21,22,23,24,25,-1,-1,-1,-1,-1,
    -1,26,27,28,29,30,31,32,33,34,35,36,37,38,39,40,
    41,42,43,44,45,46,47,48,49,50,51,-1,-1,-1,-1,-1,
    -1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,
    -1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,
    -1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,
    -1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,
    -1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,
    -1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,
    -1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,
    -1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,-1,
};

int64_t pg_b64_decode(const uint8_t *src, uint64_t n, uint8_t *dst) {
    if (n % 4 != 0) return -1;
    if (n == 0) return 0;
    uint64_t full = n;
    uint64_t pad = 0;
    if (src[n - 1] == '=') { pad++; }
    if (n >= 2 && src[n - 2] == '=') { pad++; }
    full = n - 4;  // decode all full quads except the (possibly padded) last
    uint8_t *out = dst;
    for (uint64_t i = 0; i < full; i += 4) {
        int8_t a = B64_REV[src[i]], b = B64_REV[src[i + 1]];
        int8_t c = B64_REV[src[i + 2]], d = B64_REV[src[i + 3]];
        if ((a | b | c | d) < 0) return -1;
        uint32_t v = (uint32_t(a) << 18) | (uint32_t(b) << 12) |
                     (uint32_t(c) << 6) | uint32_t(d);
        out[0] = uint8_t(v >> 16);
        out[1] = uint8_t(v >> 8);
        out[2] = uint8_t(v);
        out += 3;
    }
    // final quad with padding handling
    const uint8_t *t = src + full;
    int8_t a = B64_REV[t[0]], b = B64_REV[t[1]];
    if ((a | b) < 0) return -1;
    if (pad == 2) {
        if (t[2] != '=' || t[3] != '=') return -1;
        out[0] = uint8_t((uint32_t(a) << 2) | (uint32_t(b) >> 4));
        out += 1;
    } else if (pad == 1) {
        int8_t c = B64_REV[t[2]];
        if (c < 0 || t[3] != '=') return -1;
        uint32_t v = (uint32_t(a) << 10) | (uint32_t(b) << 4) | (uint32_t(c) >> 2);
        out[0] = uint8_t(v >> 8);
        out[1] = uint8_t(v);
        out += 2;
    } else {
        int8_t c = B64_REV[t[2]], d = B64_REV[t[3]];
        if ((c | d) < 0) return -1;
        uint32_t v = (uint32_t(a) << 18) | (uint32_t(b) << 12) |
                     (uint32_t(c) << 6) | uint32_t(d);
        out[0] = uint8_t(v >> 16);
        out[1] = uint8_t(v >> 8);
        out[2] = uint8_t(v);
        out += 3;
    }
    return int64_t(out - dst);
}

int pg_abi_version(void) { return 2; }

}  // extern "C"
