// Native runtime kernels for the host-side hot paths.
//
// Parity rationale: the reference's serving plane leans on native code for
// exactly these spots — wsaccel (C websocket masking, apps/node/
// pyproject.toml:31) plus a numpy XOR patch (apps/node/src/app/util.py:5-24),
// and protobuf's C++ for tensor payload packing. Here the equivalents are a
// word-wide XOR mask and float32<->bfloat16 wire conversion (TPU-native
// payload dtype), exported with a plain C ABI for ctypes.
//
// Build: pygrid_tpu/native/build.py shells out to g++ -O3 -shared -fPIC.

#include <cstddef>
#include <cstdint>
#include <cstring>

extern "C" {

// XOR-mask `n` bytes of `buf` in place with the 4-byte websocket mask.
// Word-wide main loop (the per-byte tail is at most 7 iterations); with -O3
// the 64-bit loop auto-vectorizes.
void pg_xor_mask(uint8_t *buf, uint64_t n, const uint8_t mask[4]) {
    uint64_t wide;
    uint8_t rep[8];
    for (int i = 0; i < 8; ++i) rep[i] = mask[i & 3];
    std::memcpy(&wide, rep, 8);

    uint64_t i = 0;
    // align to 8 so the wide loop reads aligned words
    for (; i < n && (reinterpret_cast<uintptr_t>(buf + i) & 7); ++i)
        buf[i] ^= mask[i & 3];
    // the mask phase at offset i: rotate the replicated word to match
    uint64_t phase = i & 3;
    uint64_t m = wide;
    if (phase) {
        uint8_t rot[8];
        for (int k = 0; k < 8; ++k) rot[k] = rep[(k + phase) & 3];
        std::memcpy(&m, rot, 8);
    }
    for (; i + 8 <= n; i += 8) {
        uint64_t w;
        std::memcpy(&w, buf + i, 8);
        w ^= m;
        std::memcpy(buf + i, &w, 8);
    }
    for (; i < n; ++i) buf[i] ^= mask[i & 3];
}

// float32 -> bfloat16 with round-to-nearest-even (matches XLA/ml_dtypes).
// NaNs are quieted to 0x7fc0-style payloads by the +rounding carry being
// suppressed: standard trick — if NaN, emit the truncated bits with the
// quiet bit forced.
void pg_f32_to_bf16(const uint32_t *src, uint16_t *dst, uint64_t n) {
    for (uint64_t i = 0; i < n; ++i) {
        uint32_t x = src[i];
        uint32_t exp = x & 0x7f800000u;
        if (exp == 0x7f800000u && (x & 0x007fffffu)) {
            dst[i] = static_cast<uint16_t>((x >> 16) | 0x0040u);  // quiet NaN
        } else {
            uint32_t rounding = 0x7fffu + ((x >> 16) & 1u);
            dst[i] = static_cast<uint16_t>((x + rounding) >> 16);
        }
    }
}

// bfloat16 -> float32 (exact: left shift).
void pg_bf16_to_f32(const uint16_t *src, uint32_t *dst, uint64_t n) {
    for (uint64_t i = 0; i < n; ++i)
        dst[i] = static_cast<uint32_t>(src[i]) << 16;
}

int pg_abi_version(void) { return 1; }

}  // extern "C"
