"""Build the native library on demand.

The reference ships its native pieces as pip wheels (wsaccel, protobuf);
this framework compiles its single C++ translation unit at first use with
whatever ``g++``/``clang++`` is on PATH and caches the ``.so`` next to the
source keyed by mtime. No toolchain → callers fall back to numpy paths."""

from __future__ import annotations

import logging
import os
import shutil
import subprocess
import sysconfig
from pathlib import Path

logger = logging.getLogger(__name__)

_SRC = Path(__file__).parent / "src" / "pygrid_native.cpp"


def _lib_path() -> Path:
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return Path(__file__).parent / "_build" / f"libpygrid_native{suffix}"


def ensure_built(force: bool = False) -> Path | None:
    """Compile if stale/missing; returns the library path or None."""
    lib = _lib_path()
    if (
        not force
        and lib.exists()
        and lib.stat().st_mtime >= _SRC.stat().st_mtime
    ):
        return lib
    compiler = (
        os.environ.get("CXX") or shutil.which("g++") or shutil.which("clang++")
    )
    if compiler is None:
        logger.info("pygrid_tpu.native: no C++ compiler; using numpy paths")
        return None
    lib.parent.mkdir(parents=True, exist_ok=True)
    cmd = [
        compiler, "-O3", "-shared", "-fPIC", "-std=c++17",
        str(_SRC), "-o", str(lib),
    ]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, text=True, timeout=120
        )
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired) as err:
        detail = getattr(err, "stderr", "") or str(err)
        logger.warning("pygrid_tpu.native build failed: %s", detail)
        return None
    return lib
