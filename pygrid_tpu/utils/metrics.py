"""Prometheus text-exposition helper shared by the node and network
``/metrics`` handlers (the reference has no structured metrics at all —
SURVEY §5.5)."""

from __future__ import annotations

import math


def _escape(value) -> str:
    """Prometheus label-value escaping — one bad value must not corrupt
    the whole scrape."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt(value) -> str:
    """Sample/``le`` value formatting: ``+Inf`` for infinity, ``%g``
    otherwise (Prometheus accepts scientific notation)."""
    v = float(value)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return f"{v:g}"


class Exposition:
    """Collects metric families and renders them GROUPED: one HELP/TYPE
    per name, and all of a family's samples contiguous, no matter what
    order callers mixed them in (interleaved family groups fail a strict
    Prometheus parse just like a second HELP line does)."""

    def __init__(self, prefix: str = "pygrid") -> None:
        self.prefix = prefix
        #: full name -> (help, type, [sample lines]) in declaration order
        self._families: dict[str, tuple[str, str, list[str]]] = {}

    def _family(self, full: str, help_: str, type_: str) -> list[str]:
        entry = self._families.get(full)
        if entry is None:
            entry = self._families[full] = (help_, type_, [])
        return entry[2]

    @staticmethod
    def _labels(labels: dict | None) -> str:
        if not labels:
            return ""
        inner = ",".join(
            f'{k}="{_escape(v)}"' for k, v in labels.items()
        )
        return "{" + inner + "}"

    def sample(
        self,
        name: str,
        value,
        help_: str,
        labels: dict | None = None,
        type_: str = "gauge",
    ) -> None:
        full = f"{self.prefix}_{name}"
        lines = self._family(full, help_, type_)
        lines.append(f"{full}{self._labels(labels)} {value}")

    def counter(self, name: str, value, help_: str, labels: dict | None = None) -> None:
        self.sample(name, value, help_, labels, type_="counter")

    def gauge(self, name: str, value, help_: str, labels: dict | None = None) -> None:
        self.sample(name, value, help_, labels, type_="gauge")

    def histogram(
        self,
        name: str,
        snapshot: dict,
        help_: str,
        labels: dict | None = None,
    ) -> None:
        """One histogram series from a bus snapshot: ``{"buckets":
        [(le, cumulative_count), ...], "sum": float, "count": int}``
        (``+Inf`` bucket last) — rendered as the ``_bucket``/``_sum``/
        ``_count`` member samples of one declared family."""
        full = f"{self.prefix}_{name}"
        lines = self._family(full, help_, "histogram")
        base = dict(labels or {})
        for le, count in snapshot["buckets"]:
            lines.append(
                f"{full}_bucket"
                f"{self._labels({**base, 'le': _fmt(le)})} {count}"
            )
        lines.append(f"{full}_sum{self._labels(base)} {_fmt(snapshot['sum'])}")
        lines.append(f"{full}_count{self._labels(base)} {snapshot['count']}")

    def render(self) -> str:
        out: list[str] = []
        for full, (help_, type_, lines) in self._families.items():
            out.append(f"# HELP {full} {help_}")
            out.append(f"# TYPE {full} {type_}")
            out.extend(lines)
        return "\n".join(out) + "\n"
