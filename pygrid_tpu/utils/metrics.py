"""Prometheus text-exposition helper shared by the node and network
``/metrics`` handlers (the reference has no structured metrics at all —
SURVEY §5.5)."""

from __future__ import annotations


def _escape(value) -> str:
    """Prometheus label-value escaping — one bad value must not corrupt
    the whole scrape."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


class Exposition:
    """Collects metric families; one HELP/TYPE per name no matter how many
    labeled samples (a second HELP line for a name fails the whole
    Prometheus scrape)."""

    def __init__(self, prefix: str = "pygrid") -> None:
        self.prefix = prefix
        self._lines: list[str] = []
        self._declared: set[str] = set()

    def sample(
        self,
        name: str,
        value,
        help_: str,
        labels: dict | None = None,
        type_: str = "gauge",
    ) -> None:
        full = f"{self.prefix}_{name}"
        if full not in self._declared:
            self._lines.append(f"# HELP {full} {help_}")
            self._lines.append(f"# TYPE {full} {type_}")
            self._declared.add(full)
        label_str = ""
        if labels:
            inner = ",".join(
                f'{k}="{_escape(v)}"' for k, v in labels.items()
            )
            label_str = "{" + inner + "}"
        self._lines.append(f"{full}{label_str} {value}")

    def counter(self, name: str, value, help_: str, labels: dict | None = None) -> None:
        self.sample(name, value, help_, labels, type_="counter")

    def gauge(self, name: str, value, help_: str, labels: dict | None = None) -> None:
        self.sample(name, value, help_, labels, type_="gauge")

    def render(self) -> str:
        return "\n".join(self._lines) + "\n"
