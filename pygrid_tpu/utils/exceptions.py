"""Typed grid errors.

Error-name parity with reference ``apps/node/src/app/main/core/exceptions.py``
(error class names leak into wire responses as ``{"error": str(e)}``, so the
names and default messages are part of the observable surface), plus the
execution-plane errors the reference imports from syft
(``GetNotPermittedError``, ``ResponseSignatureError``,
``EmptyCryptoPrimitiveStoreError`` — consumed at reference
``events/data_centric/syft_events.py:7-9,34-44``).
"""


class PyGridError(Exception):
    def __init__(self, message: str = "") -> None:
        super().__init__(message or self.__class__.__doc__ or self.__class__.__name__)


class AuthorizationError(PyGridError):
    """User is not authorized for this operation!"""


class WorkerNotFoundError(PyGridError):
    """Worker ID not found!"""


class RoleNotFoundError(PyGridError):
    """Role ID not found!"""


class UserNotFoundError(PyGridError):
    """User not found!"""


class GroupNotFoundError(PyGridError):
    """Group ID not found!"""


class CycleNotFoundError(PyGridError):
    """Cycle not found!"""


class FLProcessNotFoundError(PyGridError):
    """Federated learning process not found!"""


class FLProcessConflict(PyGridError):
    """FL Process already exists!"""


class ProtocolNotFoundError(PyGridError):
    """Protocol ID not found!"""


class PlanNotFoundError(PyGridError):
    """Plan ID not found!"""


class PlanInvalidError(PyGridError):
    """Plan is not valid!"""


class PlanTranslationError(PyGridError):
    """Failed to translate Plan!"""


class ModelNotFoundError(PyGridError):
    """Model ID not found!"""


class ProcessNotFoundError(PyGridError):
    """Process ID not found!"""


class ProcessFoundError(PyGridError):
    """Process already exists!"""


class ConfigsNotFoundError(PyGridError):
    """Configs not found!"""


class CheckPointNotFound(PyGridError):
    """Checkpoint not found!"""


class InvalidRequestKeyError(PyGridError):
    """Invalid request key!"""


class InvalidCredentialsError(PyGridError):
    """Invalid credentials!"""


class MissingRequestKeyError(PyGridError):
    """Missing request key!"""


class MaxCycleLimitExceededError(PyGridError):
    """There are no cycles remaining for this process."""

    def __init__(self, message: str = "") -> None:
        super().__init__(message)
        self.name = message  # reference carries the process name here


class ServerBusyError(PyGridError):
    """Server busy — generation queue is at its depth limit, retry later.

    The serving engine's backpressure signal (this framework's
    extension): admission past the bounded queue answers this typed
    error instead of piling unbounded work onto the node."""


# --- execution-plane errors (syft surface rebuilt here) ---------------------


class GetNotPermittedError(PyGridError):
    """You are not permitted to call .get() on this tensor."""


class ResponseSignatureError(PyGridError):
    """Response did not match the expected signature."""

    def __init__(self, ids_generated=None) -> None:
        super().__init__("")
        self.ids_generated = ids_generated


class EmptyCryptoPrimitiveStoreError(PyGridError):
    """Crypto primitive store is empty — a triple refill round is required.

    Carries the kwargs a crypto provider needs to synthesize the missing
    primitives (mirrors the syft refill protocol the reference relies on at
    events/data_centric/syft_events.py:34-38).
    """

    def __init__(self, kwargs_=None) -> None:
        super().__init__("")
        self.kwargs_ = dict(kwargs_ or {})


class ObjectNotFoundError(PyGridError):
    """Object not found in the store."""
