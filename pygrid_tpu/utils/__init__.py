from pygrid_tpu.utils import codes, exceptions  # noqa: F401
