"""One password-hashing implementation for both auth planes.

The reference uses bcrypt (``users/user_ops.py:29-36``) and
werkzeug hashes; neither ships in this image, so both the RBAC plane
(pygrid_tpu.users) and the data-centric session plane
(pygrid_tpu.datacentric.sessions) hash through here — pbkdf2-HMAC-SHA256,
per-user 16-byte salt, constant-time comparison.
"""

from __future__ import annotations

import hashlib
import hmac
import secrets

_ITERATIONS = 100_000


def pbkdf2(password: str, salt: bytes) -> bytes:
    return hashlib.pbkdf2_hmac(
        "sha256", password.encode(), salt, _ITERATIONS
    )


def hash_password(password: str) -> tuple[bytes, bytes]:
    """-> (salt, digest)"""
    salt = secrets.token_bytes(16)
    return salt, pbkdf2(password, salt)


def verify_password(password: str, salt: bytes, digest: bytes) -> bool:
    return hmac.compare_digest(pbkdf2(password, salt), digest)
