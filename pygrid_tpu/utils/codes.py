"""Protocol string constants — the JSON-WS/HTTP wire contract.

These are the field names and event types that clients (syft.js-style edge
workers, our own SDK in ``pygrid_tpu.client``) put on the wire, so they must
be stable. Parity surface: reference ``apps/node/src/app/main/core/codes.py``
and the ``syft.codes.REQUEST_MSG``/``RESPONSE_MSG`` constants consumed at
reference ``apps/node/src/app/main/events/__init__.py:49-56``.
"""


class MSG_FIELD:
    REQUEST_ID = "request_id"
    TYPE = "type"
    DATA = "data"
    WORKER_ID = "worker_id"
    MODEL = "model"
    MODEL_ID = "model_id"
    ALIVE = "alive"
    ALLOW_DOWNLOAD = "allow_download"
    ALLOW_REMOTE_INFERENCE = "allow_remote_inference"
    MPC = "mpc"
    PROPERTIES = "model_properties"
    SIZE = "model_size"
    SYFT_VERSION = "syft_version"
    REQUIRES_SPEED_TEST = "requires_speed_test"
    USERNAME_FIELD = "username"
    PASSWORD_FIELD = "password"
    # grid-tpu additions (node identity / status payloads)
    NODE_ID = "id"
    STATUS = "status"
    NODES = "nodes"
    MODELS = "models"
    DATASETS = "datasets"
    CPU = "cpu"
    MEM = "mem"


class CONTROL_EVENTS:
    SOCKET_PING = "socket-ping"


class WEBRTC_EVENTS:
    """Vestigial in the reference (constants only, no implementation) —
    kept for protocol-constant parity (reference core/codes.py:24-27)."""

    PEER_LEFT = "webrtc: peer-left"
    INTERNAL_MSG = "webrtc: internal-message"
    JOIN_ROOM = "webrtc: join-room"


class MODEL_CENTRIC_FL_EVENTS:
    HOST_FL_TRAINING = "model-centric/host-training"
    #: WS twin of GET /model-centric/get-model — on the negotiated binary
    #: wire the checkpoint rides the same socket as the rest of the cycle
    #: (raw bytes, no base64); this framework's extension, absent in the
    #: reference (its download is HTTP-only)
    GET_MODEL = "model-centric/get-model"
    REPORT = "model-centric/report"
    #: a sub-aggregator's pre-folded subtree report — one count-weighted
    #: partial sum standing in for fanout× individual reports (this
    #: framework's hierarchical-aggregation extension, docs/AGGREGATION.md)
    REPORT_PARTIAL = "model-centric/report-partial"
    AUTHENTICATE = "model-centric/authenticate"
    CYCLE_REQUEST = "model-centric/cycle-request"
    REPORT_METRICS = "model-centric/report-metrics"
    # secure-aggregation rounds (this framework's extension — the reference
    # has no SecAgg; names follow its model-centric/<verb> convention)
    SECAGG_ADVERTISE = "model-centric/secagg-advertise"
    SECAGG_ROSTER = "model-centric/secagg-roster"
    SECAGG_SHARES = "model-centric/secagg-shares"
    SECAGG_STATUS = "model-centric/secagg-status"
    SECAGG_UNMASK = "model-centric/secagg-unmask"


class USER_EVENTS:
    GET_ALL_USERS = "list-users"
    GET_SPECIFIC_USER = "list-user"
    SEARCH_USERS = "search-users"
    PUT_EMAIL = "put-email"
    PUT_PASSWORD = "put-password"
    # the reference assigns "put-role" to BOTH this and ROLE_EVENTS.PUT_ROLE
    # (core/codes.py:43,54), which makes user-role changes unreachable in its
    # WS table; disambiguated here
    PUT_ROLE = "put-user-role"
    PUT_GROUPS = "put-groups"
    DELETE_USER = "delete-user"
    SIGNUP_USER = "signup-user"
    LOGIN_USER = "login-user"


class ROLE_EVENTS:
    CREATE_ROLE = "create-role"
    GET_ROLE = "get-role"
    GET_ALL_ROLES = "get-all-roles"
    PUT_ROLE = "put-role"
    DELETE_ROLE = "delete-role"


class GROUP_EVENTS:
    CREATE_GROUP = "create-group"
    GET_GROUP = "get-group"
    GET_ALL_GROUPS = "get-all-groups"
    PUT_GROUP = "put-group"
    DELETE_GROUP = "delete-group"


class CYCLE:
    STATUS = "status"
    KEY = "request_key"
    PING = "ping"
    DOWNLOAD = "download"
    UPLOAD = "upload"
    VERSION = "version"
    PLANS = "plans"
    PROTOCOLS = "protocols"
    CLIENT_CONFIG = "client_config"
    SERVER_CONFIG = "server_config"
    TIMEOUT = "timeout"
    DIFF = "diff"
    AVG_PLAN = "averaging_plan"
    ACCEPTED = "accepted"
    REJECTED = "rejected"


class REQUEST_MSG:
    """Data-centric verbs (the syft.codes.REQUEST_MSG surface the reference
    node's WS router dispatches on — events/__init__.py:49-56)."""

    TYPE_FIELD = "type"
    GET_ID = "get-id"
    CONNECT_NODE = "connect-node"
    HOST_MODEL = "host-model"
    RUN_INFERENCE = "run-inference"
    #: autoregressive generation from a hosted transformer bundle — no
    #: reference analog (the reference's inference surface stops at
    #: feed-forward run-inference); exists because the transformer model
    #: family does (models/decode.py)
    RUN_GENERATION = "run-generation"
    DELETE_MODEL = "delete-model"
    LIST_MODELS = "list-models"
    AUTHENTICATE = "authentication"


class RESPONSE_MSG:
    ERROR = "error"
    SUCCESS = "success"
    NODE_ID = "id"
    INFERENCE_RESULT = "prediction"
    MODELS = "models"


class NODE_EVENTS:
    """Node↔Network WS control events (reference
    apps/network/src/app/events/__init__.py:12-15)."""

    JOIN = "join"
    FORWARD = "forward"
    MONITOR = "monitor"
    MONITOR_ANSWER = "monitor-answer"


class WORKER_STATUS:
    ONLINE = "online"
    BUSY = "busy"
    OFFLINE = "offline"


#: Number of share-holding nodes allocated per SMPC model replica
#: (reference apps/network/src/app/routes/network.py:16).
SMPC_HOST_CHUNK = 4

#: Network → node monitor heartbeat interval, seconds
#: (reference apps/network/src/app/codes.py:51-56, workers/worker.py:67-74).
MONITOR_INTERVAL_S = 15.0

#: Ping threshold after which a node is considered offline.
OFFLINE_THRESHOLD_S = 60.0
