"""Profiling and timing utilities.

The reference has no profiling at all (SURVEY.md §5.1 — stdlib logging
only); the rebuild note there calls for real instrumentation via
``jax.profiler`` + ``block_until_ready`` timers. These are the shared
helpers: a sync-correct timer (device fetch, not dispatch, marks the end),
an XLA trace context for tensorboard/perfetto dumps, and a process-wide
stats registry the node's ``/status`` surface can report."""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator


@dataclass
class TimingStats:
    count: int = 0
    total_s: float = 0.0
    min_s: float = float("inf")
    max_s: float = 0.0

    def record(self, seconds: float) -> None:
        self.count += 1
        self.total_s += seconds
        self.min_s = min(self.min_s, seconds)
        self.max_s = max(self.max_s, seconds)

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0

    def to_dict(self) -> dict:
        return {
            "count": self.count,
            "total_s": round(self.total_s, 6),
            "mean_s": round(self.mean_s, 6),
            "min_s": round(self.min_s, 6) if self.count else None,
            "max_s": round(self.max_s, 6),
        }


class _Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stats: dict[str, TimingStats] = defaultdict(TimingStats)

    def record(self, name: str, seconds: float) -> None:
        with self._lock:
            self._stats[name].record(seconds)

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            return {k: v.to_dict() for k, v in sorted(self._stats.items())}

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()


#: process-wide registry (exposed through the node /status route)
stats = _Registry()


@contextlib.contextmanager
def timed(name: str, sync: Any = None) -> Iterator[dict]:
    """Wall-clock a block; with ``sync`` (an array/pytree), end the timing
    only after the device work producing it is done (``block_until_ready``
    — dispatch returns early on accelerators)."""
    t0 = time.monotonic()
    box = {"seconds": None}
    try:
        yield box
    finally:
        target = box.get("sync", sync)
        if target is not None:
            import jax

            jax.block_until_ready(target)
        box["seconds"] = time.monotonic() - t0
        stats.record(name, box["seconds"])


def timed_call(name: str, fn: Callable, *args: Any, **kwargs: Any):
    """Run ``fn``, block on its outputs, record; returns (result, seconds)."""
    with timed(name) as box:
        result = fn(*args, **kwargs)
        box["sync"] = result
    return result, box["seconds"]


@contextlib.contextmanager
def xla_trace(log_dir: str) -> Iterator[None]:
    """``jax.profiler`` trace context → tensorboard/perfetto dump in
    ``log_dir``. The computation-tracing sibling (Plans) lives in
    :mod:`pygrid_tpu.plans`; this one is the performance profiler."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
