"""PointerTensor — client-side handle to a remote stored object.

Parity surface: syft pointer semantics the reference tests exercise —
``x.send(node)``, remote arithmetic on pointers, ``.get()``, ``.move()``,
tags/description, ``garbage_collect_data`` (reference
``tests/data_centric/test_basic_syft_operations.py:190-232`` and the intro
notebook cells 25-52).

Transport-agnostic: a pointer talks to any *location* exposing
``recv_obj_msg(msg, user=None)`` — a local :class:`VirtualWorker` directly, or
a WS client proxy (pygrid_tpu.client) that ships the same serde bytes to a
remote node.
"""

from __future__ import annotations

from typing import Any, Iterable

import numpy as np

from pygrid_tpu.plans.placeholder import fresh_id
from pygrid_tpu.runtime import messages as M
from pygrid_tpu.utils.exceptions import PyGridError


def _raise_if_error(resp: Any) -> Any:
    if isinstance(resp, M.ErrorResponse):
        from pygrid_tpu.utils import exceptions as E

        err_cls = getattr(E, resp.error_type, PyGridError)
        err = err_cls(resp.message)
        if resp.data and hasattr(err, "kwargs_"):
            err.kwargs_ = dict(resp.data)
        raise err
    return resp


class PointerTensor:
    def __init__(
        self,
        location: Any,
        id_at_location: int,
        shape: tuple | None = None,
        tags: Iterable[str] = (),
        owner_user: str | None = None,
    ) -> None:
        self.location = location
        self.id_at_location = int(id_at_location)
        self.shape = tuple(shape) if shape is not None else None
        self.tags = set(tags)
        self.owner_user = owner_user

    # --- lifecycle ----------------------------------------------------------

    def get(self, delete: bool = True) -> Any:
        """Fetch the value (permission-checked remotely)."""
        resp = self.location.recv_obj_msg(
            M.ObjectRequestMessage(obj_id=self.id_at_location, delete=delete),
            user=self.owner_user,
        )
        return _raise_if_error(resp)

    def delete(self) -> None:
        self.location.recv_obj_msg(
            M.ForceObjectDeleteMessage(obj_id=self.id_at_location),
            user=self.owner_user,
        )

    def move(self, other_location: Any) -> "PointerTensor":
        """Worker→worker transfer without the value passing through the
        client (syft ``.move(bob)``). ``other_location`` is the destination
        worker/client proxy — the returned pointer talks to it directly."""
        target_id = getattr(other_location, "id", str(other_location))
        resp = self._command(
            "send_to", [M.ref(self.id_at_location)], {"worker": target_id}
        )
        return PointerTensor(
            location=other_location,
            id_at_location=resp.id_at_location,
            shape=resp.shape,
            owner_user=self.owner_user,
        )

    # --- remote execution ---------------------------------------------------

    def _command(self, op: str, args: list, kwargs: dict) -> M.PointerResponse:
        resp = self.location.recv_obj_msg(
            M.TensorCommandMessage(
                op=op, args=args, kwargs=kwargs, return_id=fresh_id()
            ),
            user=self.owner_user,
        )
        return _raise_if_error(resp)

    def _wrap(self, resp: M.PointerResponse) -> "PointerTensor":
        return PointerTensor(
            location=self.location,
            id_at_location=resp.id_at_location,
            shape=resp.shape,
            owner_user=self.owner_user,
        )

    def _binary(self, op: str, other: Any) -> "PointerTensor":
        if isinstance(other, PointerTensor):
            arg: Any = M.ref(other.id_at_location)
        else:
            arg = np.asarray(other)
        return self._wrap(self._command(op, [M.ref(self.id_at_location), arg], {}))

    def __add__(self, other):
        return self._binary("__add__", other)

    def __sub__(self, other):
        return self._binary("__sub__", other)

    def __mul__(self, other):
        return self._binary("__mul__", other)

    def __truediv__(self, other):
        return self._binary("__truediv__", other)

    def __matmul__(self, other):
        return self._binary("__matmul__", other)

    def mm(self, other):
        return self.__matmul__(other)

    def __neg__(self):
        return self._wrap(self._command("__neg__", [M.ref(self.id_at_location)], {}))

    def remote_op(self, op: str, *args, **kwargs) -> "PointerTensor":
        """Generic method-style remote op: ``ptr.remote_op("sum", axis=0)``."""
        wire_args: list[Any] = [M.ref(self.id_at_location)]
        for a in args:
            wire_args.append(
                M.ref(a.id_at_location) if isinstance(a, PointerTensor) else a
            )
        return self._wrap(self._command(op, wire_args, kwargs))

    def sum(self, **kw):
        return self.remote_op("sum", **kw)

    def mean(self, **kw):
        return self.remote_op("mean", **kw)

    def relu(self):
        return self.remote_op("relu")

    def t(self):
        return self.remote_op("t")

    def __repr__(self) -> str:
        loc = getattr(self.location, "id", self.location)
        return (
            f"PointerTensor(location={loc!r}, id={self.id_at_location}, "
            f"shape={self.shape}, tags={sorted(self.tags)})"
        )


def send(
    x: Any,
    location: Any,
    tags: Iterable[str] = (),
    description: str = "",
    allowed_users: Iterable[str] | None = None,
    user: str | None = None,
    garbage_collect_data: bool = True,
) -> PointerTensor:
    """``x.send(worker)`` — push a value, get a pointer back."""
    resp = location.recv_obj_msg(
        M.ObjectMessage(
            obj=np.asarray(x) if not hasattr(x, "_bufferize") else x,
            id=fresh_id(),
            tags=list(tags),
            description=description,
            allowed_users=list(allowed_users) if allowed_users is not None else None,
            garbage_collect_data=garbage_collect_data,
        ),
        user=user,
    )
    resp = _raise_if_error(resp)
    return PointerTensor(
        location=location,
        id_at_location=resp.id_at_location,
        shape=resp.shape,
        tags=tags,
        owner_user=user,
    )
