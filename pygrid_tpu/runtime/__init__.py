from pygrid_tpu.runtime.store import ObjectStore, StoredObject  # noqa: F401
from pygrid_tpu.runtime.worker import VirtualWorker  # noqa: F401
from pygrid_tpu.runtime.pointers import PointerTensor, send  # noqa: F401
from pygrid_tpu.runtime import messages  # noqa: F401
