"""Object store — the party's id→tensor/model/plan map.

Parity surface: syft ``ObjectStore`` / ``worker._objects`` as the reference
uses it (tag scan over ``local_worker._objects`` at reference
``routes/data_centric/routes.py:171-189``; Redis write-through monkeypatch at
``data_centric/persistence/object_storage.py:26-62``). Entries carry the
permission metadata the reference's error path depends on
(``GetNotPermittedError`` — ``events/data_centric/syft_events.py:34-44``).

TPU-native: values are host numpy or device jax arrays — the store does not
force placement; persistence hooks (see pygrid_tpu.storage.objects) mirror the
reference's Redis write-through with a pluggable backend.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

from pygrid_tpu.plans.placeholder import fresh_id
from pygrid_tpu.utils.exceptions import (
    GetNotPermittedError,
    ObjectNotFoundError,
    PyGridError,
)


@dataclass
class StoredObject:
    value: Any
    id: int
    tags: set[str] = field(default_factory=set)
    description: str = ""
    #: None -> public; otherwise only these user names may .get() the value
    allowed_users: set[str] | None = None
    #: syft parity: whether a remote .get() removes the object here
    garbage_collect_data: bool = True

    def check_access(self, user: str | None) -> None:
        if self.allowed_users is not None and user not in self.allowed_users:
            raise GetNotPermittedError()


class ObjectStore:
    """id → StoredObject with tag search and persistence hooks."""

    def __init__(self, owner_id: str) -> None:
        self.owner_id = owner_id
        self._objects: dict[int, StoredObject] = {}
        #: write-through hooks (set by the persistence layer):
        #: on_set(owner_id, StoredObject), on_del(owner_id, obj_id)
        self.on_set: Callable[[str, StoredObject], None] | None = None
        self.on_del: Callable[[str, int], None] | None = None

    def set_obj(
        self,
        value: Any,
        id: int | None = None,
        tags: Iterable[str] = (),
        description: str = "",
        allowed_users: Iterable[str] | None = None,
        garbage_collect_data: bool = True,
        overwrite: bool = False,
    ) -> StoredObject:
        if id is not None and int(id) in self._objects and not overwrite:
            # client-chosen ids (ObjectMessage.id, command return_id) must not
            # silently replace existing objects — poisoning vector
            raise PyGridError(f"object id {id} already in use")
        obj = StoredObject(
            value=value,
            id=int(id) if id is not None else fresh_id(),
            tags=set(tags),
            description=description,
            allowed_users=set(allowed_users) if allowed_users is not None else None,
            garbage_collect_data=garbage_collect_data,
        )
        self._objects[obj.id] = obj
        if self.on_set:
            self.on_set(self.owner_id, obj)
        return obj

    def get_obj(self, obj_id: int) -> StoredObject:
        obj = self._objects.get(int(obj_id))
        if obj is None:
            raise ObjectNotFoundError(f"object {obj_id} not found")
        return obj

    def rm_obj(self, obj_id: int) -> None:
        self._objects.pop(int(obj_id), None)
        if self.on_del:
            self.on_del(self.owner_id, int(obj_id))

    def __contains__(self, obj_id: int) -> bool:
        return int(obj_id) in self._objects

    def __len__(self) -> int:
        return len(self._objects)

    def ids(self) -> list[int]:
        return list(self._objects)

    def search(self, query: Iterable[str]) -> list[StoredObject]:
        """All objects whose tags contain every query term (syft
        ``worker.search`` — reference routes.py:253-273)."""
        terms = set(query)
        return [o for o in self._objects.values() if terms <= o.tags]

    def tags(self) -> set[str]:
        out: set[str] = set()
        for o in self._objects.values():
            out |= o.tags
        return out

    def clear(self) -> None:
        self._objects.clear()
