"""VirtualWorker — the in-process party runtime.

Parity surface: syft ``VirtualWorker`` as the reference instantiates and
drives it: the Node's singleton store/executor (reference
``apps/node/src/app/main/__init__.py:10-12``), per-user workers
(``data_centric/auth/user_session.py:29-34``), the binary message entry point
``worker._recv_msg(message)`` (``events/data_centric/syft_events.py:32``) and
``local_worker.search`` / ``_objects`` scans
(``routes/data_centric/routes.py:176,263``).

TPU-native: stored tensors are jax arrays; ops execute under jit on the
accelerator; a mesh of thousands of virtual parties is cheap because a party
is a dict + id, not a process. Messages are serde dataclasses
(:mod:`pygrid_tpu.runtime.messages`) — the same bytes arrive over a WebSocket
binary frame (node transport) or a direct in-process call.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from pygrid_tpu.plans.plan import Plan
from pygrid_tpu.runtime import messages as M
from pygrid_tpu.runtime.store import ObjectStore, StoredObject
from pygrid_tpu.serde import deserialize, serialize
from pygrid_tpu.smpc.additive import AdditiveSharingTensor
from pygrid_tpu.utils import exceptions as E

# ops resolved as jnp calls on resolved array args
_ARRAY_OPS: dict[str, Callable] = {
    "__add__": jnp.add, "add": jnp.add,
    "__sub__": jnp.subtract, "sub": jnp.subtract,
    "__mul__": jnp.multiply, "mul": jnp.multiply,
    "__truediv__": jnp.divide, "div": jnp.divide,
    "__matmul__": jnp.matmul, "matmul": jnp.matmul, "mm": jnp.matmul,
    "__neg__": jnp.negative,
    "sum": jnp.sum, "mean": jnp.mean, "max": jnp.max, "min": jnp.min,
    "exp": jnp.exp, "log": jnp.log, "tanh": jnp.tanh, "sqrt": jnp.sqrt,
    "abs": jnp.abs, "sigmoid": jax.nn.sigmoid,
    "relu": lambda x: jnp.maximum(x, 0.0),
    "t": lambda x: jnp.swapaxes(x, -1, -2),
    "reshape": lambda x, *s, **k: jnp.reshape(x, s or k.get("shape")),
    "broadcast_to": lambda x, *s, **k: jnp.broadcast_to(
        x, tuple(s) or tuple(k.get("shape"))
    ),
    "argmax": jnp.argmax, "softmax": jax.nn.softmax,
}

# the ring-safe subset for int64/uint64 operands: numpy keeps 64-bit width
# and wraps on overflow — exactly mod-2^64 share arithmetic
_ARRAY_OPS_I64 = {
    "__add__": np.add, "add": np.add,
    "__sub__": np.subtract, "sub": np.subtract,
    "__mul__": np.multiply, "mul": np.multiply,
    "__matmul__": np.matmul, "matmul": np.matmul, "mm": np.matmul,
    "__neg__": np.negative,
    "sum": np.sum,
    "t": lambda x: np.swapaxes(x, -1, -2),
    "reshape": lambda x, *s, **k: np.reshape(x, s or k.get("shape")),
    # copy: remote results must own their buffers (broadcast views alias)
    "broadcast_to": lambda x, *s, **k: np.broadcast_to(
        x, tuple(s) or tuple(k.get("shape"))
    ).copy(),
}

# per-type allowlists for method dispatch: everything else is rejected
# (dunder like __setattr__ must never be remotely invokable)
_METHOD_OPS: dict[type, set[str]] = {
    AdditiveSharingTensor: {"__add__", "__sub__", "__mul__", "__matmul__"},
    Plan: set(),  # plans execute only via RunPlanMessage
}


class VirtualWorker:
    """A named party: object store + message router + known-worker mesh."""

    def __init__(self, id: str) -> None:
        self.id = str(id)
        self.store = ObjectStore(self.id)
        self._known_workers: dict[str, "VirtualWorker"] = {}
        #: set to a CryptoProvider to make this worker a triple dealer
        #: (the reference's crypto-provider node, e.g. "james" in
        #: test_basic_syft_operations.py:455-491)
        self.crypto_provider = None
        self._message_router: dict[type, Callable] = {
            M.ObjectMessage: self._handle_object,
            M.ObjectRequestMessage: self._handle_object_request,
            M.ForceObjectDeleteMessage: self._handle_delete,
            M.TensorCommandMessage: self._handle_command,
            M.RunPlanMessage: self._handle_run_plan,
            M.SearchMessage: self._handle_search,
            M.IsNoneMessage: self._handle_is_none,
            M.GetShapeMessage: self._handle_shape,
            M.CryptoRequestMessage: self._handle_crypto_request,
            M.CryptoProvideMessage: self._handle_crypto_provide,
        }

    # --- mesh ---------------------------------------------------------------

    def add_worker(self, other: "VirtualWorker") -> None:
        self._known_workers[other.id] = other
        other._known_workers[self.id] = self

    # --- transport entry points --------------------------------------------

    def _recv_msg(self, blob: bytes | bytearray, user: str | None = None) -> bytes:
        """Binary frame in, binary frame out (the reference's entry point).

        Every failure — typed grid errors and routine execution errors (shape
        mismatches etc.) — serializes to a typed ErrorResponse frame; nothing
        may escape and kill the server's frame handler.
        """
        try:
            msg = deserialize(blob)
        except Exception as err:  # noqa: BLE001 — transport boundary
            return serialize(
                M.ErrorResponse(error_type=type(err).__name__, message=str(err))
            )
        return self.recv_decoded_msg(msg, user=user)

    def recv_decoded_msg(self, msg: Any, user: str | None = None) -> bytes:
        """Dispatch an already-deserialized message; same error framing as
        ``_recv_msg`` (the WS endpoint decodes each binary frame once to
        multiplex FL events vs. tensor messages — node/events.py — and hands
        the decoded object straight here)."""
        try:
            response = self.recv_obj_msg(msg, user=user)
        except E.EmptyCryptoPrimitiveStoreError as err:
            response = M.ErrorResponse(
                error_type="EmptyCryptoPrimitiveStoreError",
                data=dict(err.kwargs_),
            )
        except E.PyGridError as err:
            response = M.ErrorResponse(
                error_type=type(err).__name__, message=str(err)
            )
        except Exception as err:  # noqa: BLE001 — transport boundary
            response = M.ErrorResponse(
                error_type=type(err).__name__, message=str(err)
            )
        return serialize(response)

    def recv_obj_msg(self, msg: Any, user: str | None = None) -> Any:
        handler = self._message_router.get(type(msg))
        if handler is None:
            raise E.PyGridError(f"unknown message type {type(msg).__name__}")
        return handler(msg, user)

    # --- argument resolution ------------------------------------------------

    def _resolve(self, v: Any, user: str | None, sources: list | None = None):
        """Deref ``{"__ref__": id}`` args. Every deref is permission-checked
        against the session user — computing on a private tensor would
        otherwise be a laundering bypass of GetNotPermittedError."""
        if M.is_ref(v):
            obj = self.store.get_obj(v["__ref__"])
            obj.check_access(user)
            if sources is not None:
                sources.append(obj)
            return obj.value
        if isinstance(v, list):
            return [self._resolve(x, user, sources) for x in v]
        return v

    @staticmethod
    def _derived_permissions(sources: list) -> set[str] | None:
        """Results inherit the most restrictive source policy: intersection
        of all non-public allowed_users sets (None == public)."""
        allowed: set[str] | None = None
        for obj in sources:
            if obj.allowed_users is not None:
                allowed = (
                    set(obj.allowed_users)
                    if allowed is None
                    else allowed & obj.allowed_users
                )
        return allowed

    # --- handlers -----------------------------------------------------------

    def _handle_object(self, msg: M.ObjectMessage, user: str | None):
        # id-reuse rejection lives in ObjectStore.set_obj, covering every
        # path that stores at a client-chosen id (object push, command
        # return_id, plan return_id)
        obj = self.store.set_obj(
            value=msg.obj,
            id=msg.id,
            tags=msg.tags,
            description=msg.description,
            allowed_users=msg.allowed_users,
            garbage_collect_data=msg.garbage_collect_data,
        )
        shape = list(getattr(msg.obj, "shape", ()) or ())
        return M.PointerResponse(
            id_at_location=obj.id, location=self.id, shape=shape, tags=msg.tags
        )

    def _handle_object_request(self, msg: M.ObjectRequestMessage, user: str | None):
        obj = self.store.get_obj(msg.obj_id)
        obj.check_access(user)
        value = obj.value
        if msg.delete and obj.garbage_collect_data:
            self.store.rm_obj(msg.obj_id)
        return value

    def _handle_delete(self, msg: M.ForceObjectDeleteMessage, user: str | None):
        if msg.obj_id in self.store:
            # the destructive path is permission-gated like the read path
            self.store.get_obj(msg.obj_id).check_access(user)
            self.store.rm_obj(msg.obj_id)
        return {"status": "ok"}

    def _handle_command(self, msg: M.TensorCommandMessage, user: str | None):
        if msg.op == "send_to":
            return self._handle_move(msg, user)
        sources: list = []
        args = [self._resolve(a, user, sources) for a in msg.args]
        kwargs = {k: self._resolve(v, user, sources) for k, v in msg.kwargs.items()}
        result = self._execute_op(msg.op, args, kwargs)
        obj = self.store.set_obj(
            result,
            id=msg.return_id,
            allowed_users=self._derived_permissions(sources),
        )
        return M.PointerResponse(
            id_at_location=obj.id,
            location=self.id,
            shape=list(getattr(result, "shape", ()) or ()),
        )

    def _handle_move(self, msg: M.TensorCommandMessage, user: str | None):
        """Worker→worker move: full StoredObject metadata travels with the
        value (a private tensor must stay private on the target), origin copy
        is removed, and the target's pointer is the response."""
        if not (msg.args and M.is_ref(msg.args[0])):
            raise E.PyGridError("send_to requires an object reference")
        target_id = msg.kwargs.get("worker")
        target = self._known_workers.get(target_id)
        if target is None:
            raise E.WorkerNotFoundError()
        obj = self.store.get_obj(msg.args[0]["__ref__"])
        obj.check_access(user)
        resp = target.recv_obj_msg(
            M.ObjectMessage(
                obj=obj.value,
                tags=sorted(obj.tags),
                description=obj.description,
                allowed_users=(
                    sorted(obj.allowed_users)
                    if obj.allowed_users is not None
                    else None
                ),
                garbage_collect_data=obj.garbage_collect_data,
            ),
            user=user,
        )
        self.store.rm_obj(obj.id)  # a move leaves no copy behind
        return resp

    def _execute_op(self, op: str, args: list, kwargs: dict) -> Any:
        first = args[0] if args else None
        for typ, allowed_ops in _METHOD_OPS.items():
            if isinstance(first, typ):
                if op not in allowed_ops:
                    raise E.PyGridError(
                        f"{typ.__name__} does not support remote op {op!r}"
                    )
                return getattr(first, op)(*args[1:], **kwargs)
        # 64-bit integer arrays (SMPC ring shares travel as int64) must keep
        # full width and wrap mod 2^64 — jnp would truncate to int32 under
        # the default x64-off config, so they run on numpy instead. Only
        # non-scalar operands count: Python int scalars arrive as 0-d int64
        # and must not hijack float-tensor ops like ``ptr / 2``.
        tensor_args = [
            a for a in args if isinstance(a, np.ndarray) and a.ndim >= 1
        ]
        if tensor_args and all(
            a.dtype.kind in "iu" and a.dtype.itemsize == 8
            for a in tensor_args
        ):
            fn = _ARRAY_OPS_I64.get(op)
            if fn is None:
                raise E.PyGridError(
                    f"op {op!r} not permitted on 64-bit integer tensors"
                )
            with np.errstate(over="ignore"):
                return fn(*args, **kwargs)
        fn = _ARRAY_OPS.get(op)
        if fn is None:
            raise E.PyGridError(f"op {op!r} not permitted")
        args = [jnp.asarray(a) if isinstance(a, np.ndarray) else a for a in args]
        return fn(*args, **kwargs)

    def _handle_run_plan(self, msg: M.RunPlanMessage, user: str | None):
        obj = self.store.get_obj(msg.plan_id)
        obj.check_access(user)  # a private Plan is a private model
        plan = obj.value
        if not isinstance(plan, Plan):
            raise E.PlanNotFoundError(f"object {msg.plan_id} is not a Plan")
        sources: list = [obj]
        args = [self._resolve(a, user, sources) for a in msg.args]
        result = plan(*args)
        stored = self.store.set_obj(
            result,
            id=msg.return_id,
            allowed_users=self._derived_permissions(sources),
        )
        return M.PointerResponse(
            id_at_location=stored.id,
            location=self.id,
            shape=list(getattr(result, "shape", ()) or ()),
        )

    # --- crypto-provider plane (cross-node Beaver dealing) -------------------

    def _require_provider(self):
        if self.crypto_provider is None:
            raise E.PyGridError(f"worker {self.id!r} is not a crypto provider")
        return self.crypto_provider

    def _handle_crypto_request(self, msg: M.CryptoRequestMessage, user: str | None):
        """Deal one primitive: generate (or pop from the strict store — may
        raise ``EmptyCryptoPrimitiveStoreError``, which ``_recv_msg``
        serializes back with the refill kwargs), then push each party's
        share arrays to the party workers over the known-worker mesh."""
        from pygrid_tpu.smpc import ring as R

        provider = self._require_provider()
        n = len(msg.party_ids)
        if n < 2:
            raise E.PyGridError("need at least 2 parties")
        # resolve every target BEFORE drawing the primitive: a bad party id
        # must not consume strict-store stock
        targets = []
        for pid in msg.party_ids:
            target = self if pid == self.id else self._known_workers.get(pid)
            if target is None:
                raise E.WorkerNotFoundError(f"unknown party worker {pid!r}")
            targets.append(target)
        if msg.op == "trunc":
            components = provider.trunc_pair(
                tuple(msg.shape_x), int(msg.shape_y[0]), n
            )
        else:
            components = provider.triple(
                msg.op, tuple(msg.shape_x), tuple(msg.shape_y), n
            )
        ids: list[list[int]] = []
        pushed: list[tuple[Any, int]] = []  # (target, obj_id) for rollback
        try:
            for i, target in enumerate(targets):
                row = []
                for stacked in components:
                    # wire format: one party's slice as int64 (two's complement)
                    arr = R.from_ring(
                        R.Ring64(stacked.lo[i], stacked.hi[i])
                    ).astype(np.int64)
                    resp = target.recv_obj_msg(
                        M.ObjectMessage(obj=arr), user=user
                    )
                    if isinstance(resp, M.ErrorResponse):
                        raise E.PyGridError(
                            f"dealing to {msg.party_ids[i]!r} failed: "
                            f"{resp.message}"
                        )
                    row.append(resp.id_at_location)
                    pushed.append((target, resp.id_at_location))
                ids.append(row)
        except Exception:
            for target, obj_id in pushed:  # best-effort: no orphaned shares
                try:
                    target.recv_obj_msg(
                        M.ForceObjectDeleteMessage(obj_id=obj_id), user=user
                    )
                except Exception:  # noqa: BLE001 — cleanup path
                    pass
            raise
        return M.CryptoDealResponse(party_ids=list(msg.party_ids), ids=ids)

    def _handle_crypto_provide(self, msg: M.CryptoProvideMessage, user: str | None):
        provider = self._require_provider()
        provider.provide(
            msg.op,
            tuple(msg.shape_x),
            tuple(msg.shape_y),
            msg.n_parties,
            msg.n_instances,
        )
        return {"status": "ok"}

    @staticmethod
    def _visible_to(obj: StoredObject, user: str | None) -> bool:
        return obj.allowed_users is None or user in obj.allowed_users

    def _handle_search(self, msg: M.SearchMessage, user: str | None):
        # private objects are invisible to other users: even their ids/shapes
        # would leak handles for probing
        found = [o for o in self.store.search(msg.query) if self._visible_to(o, user)]
        return [
            M.PointerResponse(
                id_at_location=o.id,
                location=self.id,
                shape=list(getattr(o.value, "shape", ()) or ()),
                tags=sorted(o.tags),
            )
            for o in found
        ]

    def _handle_is_none(self, msg: M.IsNoneMessage, user: str | None):
        if msg.obj_id not in self.store:
            return True
        # inaccessible == indistinguishable from absent
        return not self._visible_to(self.store.get_obj(msg.obj_id), user)

    def _handle_shape(self, msg: M.GetShapeMessage, user: str | None):
        obj = self.store.get_obj(msg.obj_id)
        obj.check_access(user)
        return list(getattr(obj.value, "shape", ()) or ())

    # --- convenience (syft-style local API) ---------------------------------

    def search(self, *query: str) -> list[StoredObject]:
        return self.store.search(query)

    def __repr__(self) -> str:
        return f"VirtualWorker(id={self.id!r}, objects={len(self.store)})"
