"""Wire messages for the party runtime — the binary-frame protocol.

Parity surface: the syft wire messages the reference forwards opaquely
(``forward_binary_message`` → ``worker._recv_msg(message)`` at reference
``events/data_centric/syft_events.py:18-45``). Here the message set is
first-party: each message is a serde-registered dataclass; a worker routes on
the class (``VirtualWorker._message_router``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from pygrid_tpu.serde import register_serde


def _simple_serde(cls):
    """Dataclass -> dict serde using the declared fields."""
    names = [f for f in cls.__dataclass_fields__]

    def _bufferize(self):
        return {n: getattr(self, n) for n in names}

    def _unbufferize(klass, data):
        kwargs = {n: data[n] for n in names}
        for n, f in cls.__dataclass_fields__.items():
            if f.type in ("set[str]", "set") and kwargs[n] is not None:
                kwargs[n] = set(kwargs[n])
        return klass(**kwargs)

    cls._bufferize = _bufferize
    cls._unbufferize = classmethod(_unbufferize)
    return register_serde(cls, name=f"pygrid.msg.{cls.__name__}")


@_simple_serde
@dataclass
class ObjectMessage:
    """Push an object into the receiving worker's store (tensor ``.send()``)."""

    obj: Any
    id: int | None = None
    tags: list[str] = field(default_factory=list)
    description: str = ""
    allowed_users: list[str] | None = None
    garbage_collect_data: bool = True


@_simple_serde
@dataclass
class ObjectRequestMessage:
    """Fetch an object's value (pointer ``.get()``).

    Permission-checked against the *session* user supplied by the transport
    (``recv_obj_msg(msg, user=...)``) — identity never rides in the message,
    where a client could assert someone else's name.
    """

    obj_id: int
    delete: bool = True  # syft gc: a successful .get() removes the remote obj


@_simple_serde
@dataclass
class ForceObjectDeleteMessage:
    obj_id: int


@_simple_serde
@dataclass
class TensorCommandMessage:
    """Execute one op on stored objects: result ids are assigned remotely.

    ``op`` is a name in the command table (jnp ufuncs, methods, operators);
    ``arg_ids``/``kwargs`` may reference stored objects by id via
    ``{"__ref__": id}`` or carry literal values.
    """

    op: str
    args: list[Any] = field(default_factory=list)
    kwargs: dict[str, Any] = field(default_factory=dict)
    return_id: int | None = None


@_simple_serde
@dataclass
class RunPlanMessage:
    """Execute a stored Plan on stored/literal args."""

    plan_id: int
    args: list[Any] = field(default_factory=list)
    return_id: int | None = None


@_simple_serde
@dataclass
class SearchMessage:
    query: list[str] = field(default_factory=list)


@_simple_serde
@dataclass
class IsNoneMessage:
    obj_id: int


@_simple_serde
@dataclass
class GetShapeMessage:
    obj_id: int


@_simple_serde
@dataclass
class CryptoRequestMessage:
    """Ask a crypto-provider worker to deal one primitive to the parties.

    ``op`` ∈ {"mul", "matmul", "trunc"}. For triples, ``shape_x``/``shape_y``
    are the operand shapes; for a truncation pair, ``shape_x`` is the value
    shape and ``shape_y`` carries ``[scale]``. The provider pushes each
    party's share arrays to the named party workers (its known-worker mesh)
    and answers with the stored object ids (:class:`CryptoDealResponse`).
    A strict-store provider with no stocked primitive raises
    ``EmptyCryptoPrimitiveStoreError`` — the refill round-trip the reference
    serializes over the wire (reference syft_events.py:34-45).
    """

    op: str
    shape_x: list[int]
    shape_y: list[int]
    party_ids: list[str] = field(default_factory=list)


@_simple_serde
@dataclass
class CryptoProvideMessage:
    """Refill the provider's primitive store (response to an empty-store
    error; mirrors syft's ``provide_primitives`` round)."""

    op: str
    shape_x: list[int]
    shape_y: list[int]
    n_parties: int
    n_instances: int = 1


@_simple_serde
@dataclass
class CryptoDealResponse:
    """Ids of the dealt share objects: ``ids[i]`` lists party i's object ids
    (one per component — [a,b,c] for a triple, [r,r'] for a trunc pair)."""

    party_ids: list[str]
    ids: list[list[int]] = field(default_factory=list)


@_simple_serde
@dataclass
class ErrorResponse:
    error_type: str
    message: str = ""
    #: extra payload (e.g. crypto-store refill kwargs)
    data: dict = field(default_factory=dict)


@_simple_serde
@dataclass
class PointerResponse:
    """Acknowledges a stored object: its remote id + metadata."""

    id_at_location: int
    location: str
    shape: list[int] | None = None
    tags: list[str] = field(default_factory=list)


def ref(obj_id: int) -> dict:
    """Build an argument reference to a stored object."""
    return {"__ref__": int(obj_id)}


def is_ref(v: Any) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"__ref__"}
