"""The Node's WebSocket endpoint.

Parity surface: reference ``events/__init__.py:90-107`` (``socket_api``: one
WS route at ``/``; JSON and binary frames through ``route_requests``; worker
unbound on socket close) served by gevent-websocket. Here: aiohttp WS with
the blocking handler work pushed to the default executor so jax/sqlite calls
never stall the event loop. The reference's numpy XOR-masking fast path
(``util.py:5-24``) corresponds to the native masking extension in
``pygrid_tpu/native`` (aiohttp itself masks frames in C already).
"""

from __future__ import annotations

import asyncio

from aiohttp import WSMsgType, web

from pygrid_tpu.node.events import Connection, _handler_of, route_requests


async def ws_handler(request: web.Request) -> web.StreamResponse:
    ctx = request.app["node"]
    if (
        request.headers.get("Upgrade", "").lower() != "websocket"
    ):  # plain GET / → dashboard for browsers, JSON for programs
        # (reference serves templates/index.html here, app/__init__.py:173)
        if "text/html" in request.headers.get("Accept", ""):
            from pygrid_tpu.node.dashboard import render

            return web.Response(
                text=render(ctx.id), content_type="text/html"
            )
        return web.json_response(
            {"node_id": ctx.id, "message": "pygrid-tpu node"}
        )

    ws = web.WebSocketResponse(max_msg_size=256 * 1024 * 1024)
    await ws.prepare(request)
    conn = Connection(ctx, socket=ws)
    loop = asyncio.get_running_loop()
    try:
        async for msg in ws:
            if msg.type == WSMsgType.TEXT:
                payload: str | bytes = msg.data
            elif msg.type == WSMsgType.BINARY:
                payload = msg.data  # already bytes — no defensive copy on
                # the megabyte report path; handlers never mutate frames
            else:
                continue
            response = await loop.run_in_executor(
                None, route_requests, ctx, payload, conn
            )
            try:
                if isinstance(response, (bytes, bytearray)):
                    await ws.send_bytes(bytes(response))
                elif response is not None:
                    await ws.send_str(response)
            except (ConnectionError, RuntimeError):
                # the peer vanished while the handler ran — a dropped
                # response to a dropped client is not a server error
                break
    finally:
        _handler_of(ctx).remove(ws)
    return ws
