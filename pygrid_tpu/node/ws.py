"""The Node's WebSocket endpoint.

Parity surface: reference ``events/__init__.py:90-107`` (``socket_api``: one
WS route at ``/``; JSON and binary frames through ``route_requests``; worker
unbound on socket close) served by gevent-websocket. Here: aiohttp WS with
the blocking handler work pushed to the default executor so jax/sqlite calls
never stall the event loop. The reference's numpy XOR-masking fast path
(``util.py:5-24``) corresponds to the native masking extension in
``pygrid_tpu/native`` (aiohttp itself masks frames in C already).

Wire v2: clients may offer the ``pygrid.wire.v2`` websocket subprotocol
(optionally ``+zstd``/``+zlib``) during the upgrade. On a negotiated
connection every binary frame carries a one-byte codec tag and may be
compressed; TEXT frames stay legacy JSON, and clients that never offer the
subprotocol get the v1 framing untouched — the fallback needs no server
configuration.
"""

from __future__ import annotations

import asyncio
import os
import time
from concurrent.futures import ThreadPoolExecutor

from aiohttp import WSMsgType, web

from pygrid_tpu import telemetry
from pygrid_tpu.node.events import Connection, _handler_of, route_requests
from pygrid_tpu.serde import (
    decode_frame_traced,
    encode_frame,
    offered_subprotocols,
    serialize,
    subprotocol_codec,
    subprotocol_traced,
)
from pygrid_tpu.telemetry import trace

#: every subprotocol variant this build can serve — aiohttp picks the
#: first of the client's offers present here (client preference wins)
_SERVER_SUBPROTOCOLS = tuple(offered_subprotocols("auto"))

#: dedicated bounded pool for WS handler work, replacing the loop's
#: default executor: generation COMPUTE now runs on each serving
#: engine's own thread (pygrid_tpu/serving), so a generation burst can
#: no longer monopolize the process-wide default executor that other
#: subsystems share. A generation frame still *occupies* one of these
#: threads while it waits on the engine future (each WS connection
#: processes one frame at a time, so that's one thread per generating
#: client) — deployments expecting more than PYGRID_WS_THREADS
#: concurrent generating sockets should raise the knob or point bulk
#: generation at the async HTTP route, which holds no thread at all.
_WS_EXECUTOR = ThreadPoolExecutor(
    max_workers=int(os.environ.get("PYGRID_WS_THREADS", "32")),
    thread_name_prefix="pygrid-ws",
)


async def ws_handler(request: web.Request) -> web.StreamResponse:
    ctx = request.app["node"]
    if (
        request.headers.get("Upgrade", "").lower() != "websocket"
    ):  # plain GET / → dashboard for browsers, JSON for programs
        # (reference serves templates/index.html here, app/__init__.py:173)
        if "text/html" in request.headers.get("Accept", ""):
            from pygrid_tpu.node.dashboard import render

            return web.Response(
                text=render(ctx.id), content_type="text/html"
            )
        return web.json_response(
            {"node_id": ctx.id, "message": "pygrid-tpu node"}
        )

    ws = web.WebSocketResponse(
        max_msg_size=256 * 1024 * 1024, protocols=_SERVER_SUBPROTOCOLS
    )
    await ws.prepare(request)
    conn = Connection(ctx, socket=ws)
    conn.wire_v2, conn.wire_codec = subprotocol_codec(ws.ws_protocol)
    #: trace headers on frames ONLY when the peer negotiated the
    #: ``.trace`` subprotocol variant — a plain-v2 peer's decoder
    #: predates the tag bit and would reject it
    wire_trace = subprotocol_traced(ws.ws_protocol)
    loop = asyncio.get_running_loop()
    codec_label = conn.wire_codec or ("v2" if conn.wire_v2 else "v1")

    def _unframe_route_frame(payload):
        if conn.wire_v2 and not isinstance(payload, str):
            t0 = time.perf_counter()
            try:
                payload, frame_trace = decode_frame_traced(payload)
            except ValueError as err:
                # a bad frame on a negotiated connection is a peer bug —
                # answer typed, keep the socket alive
                return encode_frame(
                    serialize({"error": f"bad wire-v2 frame: {err}"})
                )
            telemetry.observe(
                "ws_frame_decode_seconds", time.perf_counter() - t0
            )
            # one-shot: route_requests consumes it for the handler span
            conn.incoming_trace = trace.from_bytes(frame_trace)
        response = route_requests(ctx, payload, conn)
        # one-shot handler hint: a response embedding an already-
        # compressed payload (cached checkpoint) skips the envelope
        # codec pass — it would be redundant work per worker
        suppress, conn.suppress_frame_codec = conn.suppress_frame_codec, False
        served, conn.last_trace = conn.last_trace, None
        if conn.wire_v2 and isinstance(
            response, (bytes, bytearray, memoryview)
        ):
            codec = None if suppress else conn.wire_codec
            response = encode_frame(
                bytes(response), codec,
                trace=trace.to_bytes(served) if wire_trace else None,
            )
        return response

    def _process(payload):
        """Unframe → route → frame, all ON THE EXECUTOR THREAD: per-frame
        decompression/compression of megabyte payloads must not stall the
        event loop any more than the handlers themselves. (Byte counters:
        TEXT frames count characters — the JSON protocol is ASCII apart
        from user-supplied strings, so the drift is negligible and the
        alternative is re-encoding megabyte report frames.)"""
        telemetry.incr(
            "wire_bytes_total", len(payload), direction="in",
            codec=codec_label,
        )
        response = _unframe_route_frame(payload)
        if response is not None:
            telemetry.incr(
                "wire_bytes_total", len(response), direction="out",
                codec=codec_label,
            )
        return response

    try:
        async for msg in ws:
            if msg.type == WSMsgType.TEXT:
                payload: str | bytes = msg.data
            elif msg.type == WSMsgType.BINARY:
                payload = msg.data  # already bytes — no defensive copy on
                # the megabyte report path; handlers never mutate frames
            else:
                continue
            response = await loop.run_in_executor(
                _WS_EXECUTOR, _process, payload
            )
            try:
                if isinstance(response, (bytes, bytearray, memoryview)):
                    await ws.send_bytes(bytes(response))
                elif response is not None:
                    await ws.send_str(response)
            except (ConnectionError, RuntimeError):
                # the peer vanished while the handler ran — a dropped
                # response to a dropped client is not a server error
                break
    finally:
        _handler_of(ctx).remove(ws)
    return ws
