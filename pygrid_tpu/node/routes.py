"""Node HTTP routes: model-centric, data-centric, users.

Parity surface: reference ``apps/node/src/app/main/routes/model_centric/
routes.py`` (cycle-request/speed-test/report/get-protocol/get-model/get-plan/
authenticate/retrieve-model — see SURVEY.md §2.1) and
``routes/data_centric/routes.py`` (models/detailed-models-list/identity/
status/workers/serve-model/dataset-tags/search-encrypted-models/search), plus
the users HTTP CRUD. Status codes mirror the reference: 400 bad request,
401 invalid request key, 404 model missing, 500 otherwise.
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
from typing import Any

from aiohttp import web

from pygrid_tpu import telemetry
from pygrid_tpu.node import NodeContext, __version__
from pygrid_tpu.node.events import (
    Connection,
    authenticate as ws_authenticate,
    cycle_request as ws_cycle_request,
    report as ws_report,
    _USER_HANDLERS,
)
from pygrid_tpu.plans.plan import Plan
from pygrid_tpu.serde import deserialize
from pygrid_tpu.utils import exceptions as E
from pygrid_tpu.utils.codes import MSG_FIELD

logger = logging.getLogger(__name__)

SPEED_TEST_SAMPLE_BYTES = 64 * 1024 * 1024  # reference: 64MB, routes.py:80-83


def _ctx(request: web.Request) -> NodeContext:
    return request.app["node"]


async def _off_loop(fn, *args):
    """Run a blocking callable on the default executor — the HTTP routes'
    door for sync WS-handler bridges and model-scale serde/base64 work
    (gridlint GL3: one megabyte decode on the loop stalls every socket
    the process serves). The caller's contextvars are carried across:
    the telemetry middleware's trace span lives in a contextvar, and an
    executor thread does not inherit it — without the copy, a bridged
    ``report`` would record no trace on the cycle timeline."""
    import asyncio
    import contextvars

    ctx = contextvars.copy_context()
    return await asyncio.get_running_loop().run_in_executor(
        None, lambda: ctx.run(fn, *args)
    )


def _json_error(err: Exception, status: int) -> web.Response:
    return web.json_response({"error": str(err)}, status=status)


def _status_for(err: Exception) -> int:
    if isinstance(err, E.ServerBusyError):
        return 503  # backpressure: retryable, not a client defect
    if isinstance(err, E.InvalidRequestKeyError):
        return 401
    if isinstance(
        err,
        (
            E.ModelNotFoundError,
            E.CheckPointNotFound,
            E.FLProcessNotFoundError,
        ),
    ):
        return 404
    if isinstance(err, E.PyGridError):
        return 400
    return 500


# ── model-centric ────────────────────────────────────────────────────────────


async def mc_cycle_request(request: web.Request) -> web.Response:
    """HTTP mirror of the WS cycle-request (reference routes.py:37-60)."""
    try:
        body = json.loads(await request.text())
    except (json.JSONDecodeError, UnicodeDecodeError) as err:
        return _json_error(err, 400)
    response = await _off_loop(
        ws_cycle_request,
        _ctx(request), {MSG_FIELD.DATA: body}, Connection(_ctx(request)),
    )
    return web.json_response(response[MSG_FIELD.DATA])


async def mc_speed_test(request: web.Request) -> web.Response:
    """(reference routes.py:62-99) download sample / ping / upload sink."""
    worker_id = request.query.get("worker_id")
    random = request.query.get("random")
    is_ping = request.query.get("is_ping")
    if not worker_id or not random:
        return _json_error(E.PyGridError(""), 400)
    if request.method == "GET" and is_ping is None:
        try:
            size = int(request.query.get("size", SPEED_TEST_SAMPLE_BYTES))
        except ValueError as err:
            return _json_error(err, 400)
        # unauthenticated endpoint: cap at the reference's 64MB sample, and
        # stream it in chunks — materializing the full sample per request
        # would let anonymous callers burn 64MB of RSS each
        size = max(0, min(size, SPEED_TEST_SAMPLE_BYTES))
        response = web.StreamResponse(
            headers={
                "Content-Type": "application/octet-stream",
                "Content-Length": str(size),
            }
        )
        await response.prepare(request)
        chunk = b"x" * min(size, 1 << 20)
        sent = 0
        while sent < size:
            n = min(size - sent, len(chunk))
            await response.write(chunk[:n])
            sent += n
        await response.write_eof()
        return response
    if request.method == "POST":
        await request.read()  # upload sink
    return web.json_response({})


async def mc_report(request: web.Request) -> web.Response:
    try:
        # an FL report body is megabytes of base64 diff — parsing it is
        # CPU work the loop must not pay (same reasoning as _off_loop)
        body = await _off_loop(json.loads, await request.text())
    except (json.JSONDecodeError, UnicodeDecodeError) as err:
        return _json_error(err, 400)
    response = await _off_loop(
        ws_report,
        _ctx(request), {MSG_FIELD.DATA: body}, Connection(_ctx(request)),
    )
    return web.json_response(response[MSG_FIELD.DATA])


async def mc_authenticate(request: web.Request) -> web.Response:
    try:
        body = json.loads(await request.text())
    except (json.JSONDecodeError, UnicodeDecodeError) as err:
        return _json_error(err, 400)
    response = await _off_loop(
        ws_authenticate,
        _ctx(request), {MSG_FIELD.DATA: body}, Connection(_ctx(request)),
    )
    return web.json_response(response[MSG_FIELD.DATA])


def _require_query(request: web.Request, *names: str) -> list[str]:
    """Explicit 400 bodies for absent params (the reference's download
    routes answer with named missing-key messages, routes.py:163-250, not
    a generic 401)."""
    missing = [n for n in names if not request.query.get(n)]
    if missing:
        raise E.MissingRequestKeyError(
            f"missing query parameter(s): {', '.join(missing)}"
        )
    return [request.query[n] for n in names]


def _validated_cycle(ctx: NodeContext, request: web.Request, fl_process_id: int):
    """request_key gate shared by the three download routes
    (reference routes.py:163-250). Returns the validated cycle so
    callers can attribute wire bytes to its timeline."""
    worker_id, request_key = _require_query(request, "worker_id", "request_key")
    cycle = ctx.fl.cycle_manager.last(fl_process_id)
    worker = ctx.fl.worker_manager.get(id=worker_id)
    ctx.fl.cycle_manager.validate(worker.id, cycle.id, request_key)
    return cycle


async def mc_get_model(request: web.Request) -> web.Response:
    ctx = _ctx(request)
    try:
        model_id = int(_require_query(request, "model_id")[0])
        model = ctx.fl.model_manager.get(id=model_id)
        cycle = _validated_cycle(ctx, request, model.fl_process_id)
        # ?codec=zlib|zstd → the wire-v2 frame envelope, compressed once
        # per checkpoint (blob cache) and unwrapped client-side with
        # decode_frame. The response header is the client's only signal —
        # an old node ignores the param and serves raw, so absence of the
        # header means raw bytes.
        from pygrid_tpu.serde import available_codecs

        codec = request.query.get("codec")
        codec = codec if codec in available_codecs() else None
        blob = ctx.fl.model_manager.load_encoded(
            model_id, precision=request.query.get("precision"), codec=codec
        )
        telemetry.timeline.add_bytes(
            cycle.id, "download", codec or "http", len(blob)
        )
        telemetry.incr(
            "model_download_bytes_total", len(blob), codec=codec or "http"
        )
        headers = {"X-PyGrid-Wire": "v2-frame"} if codec else {}
        return web.Response(
            body=blob,
            content_type="application/octet-stream",
            headers=headers,
        )
    except Exception as err:  # noqa: BLE001 — HTTP boundary
        return _json_error(err, _status_for(err))


async def mc_get_plan(request: web.Request) -> web.Response:
    ctx = _ctx(request)
    try:
        plan_id = int(_require_query(request, "plan_id")[0])
        variant = request.query.get("receive_operations_as", "list")
        plan = ctx.fl.plan_manager.get(id=plan_id, is_avg_plan=False)
        _validated_cycle(ctx, request, plan.fl_process_id)
        blob = ctx.fl.plan_manager.get_variant(plan_id, variant)
        return web.Response(
            body=blob, content_type="application/octet-stream"
        )
    except Exception as err:  # noqa: BLE001 — HTTP boundary
        return _json_error(err, _status_for(err))


async def mc_get_protocol(request: web.Request) -> web.Response:
    ctx = _ctx(request)
    try:
        protocol_id = int(_require_query(request, "protocol_id")[0])
        protocol = ctx.fl.protocol_manager.get(id=protocol_id)
        _validated_cycle(ctx, request, protocol.fl_process_id)
        return web.Response(
            body=protocol.value, content_type="application/octet-stream"
        )
    except Exception as err:  # noqa: BLE001 — HTTP boundary
        return _json_error(err, _status_for(err))


async def mc_req_join(request: web.Request) -> web.Response:
    """Probabilistic cycle-admission decision (reference routes.py:287-468,
    the ``/req-join`` Poisson worker-selection endpoint). Accepts by model
    name+version (or fl_process id), worker speeds and id; returns
    ``{"status": "accepted"|"rejected"}`` with 200/400 like the reference."""
    import datetime as dt

    from pygrid_tpu.federated.selection import should_admit

    ctx = _ctx(request)
    try:
        q = request.query
        if q.get("model_id"):
            process = ctx.fl.process_manager.first(id=int(q["model_id"]))
        else:
            filters: dict[str, Any] = {"name": q.get("name")}
            if q.get("version"):
                filters["version"] = q["version"]
            process = ctx.fl.process_manager.first(**filters)
        cycle = ctx.fl.cycle_manager.last(process.id)
        server_config = ctx.fl.process_manager.get_configs(
            fl_process_id=process.id, is_server_config=True
        )
        worker_id = q.get("worker_id", "")
        time_left = None
        if cycle.end is not None:
            now = dt.datetime.now(dt.timezone.utc).replace(tzinfo=None)
            time_left = (cycle.end - now).total_seconds()
        decision = should_admit(
            server_config=server_config,
            cycle_sequence=cycle.sequence,
            cycle_time_left=time_left,
            workers_in_cycle=ctx.fl.cycle_manager.workers_in_cycle(cycle.id),
            already_in_cycle=ctx.fl.cycle_manager.is_assigned(
                cycle.id, worker_id
            ),
            last_participation=ctx.fl.cycle_manager.last_participation(
                process.id, worker_id
            ),
            up_speed=float(q.get("up_speed", 0)),
            down_speed=float(q.get("down_speed", 0)),
            # observed join rate; the reference hard-codes 5/unit-time
            # (routes.py:384) — here overridable per request for ops/tests
            request_rate=float(q.get("request_rate", 5.0)),
        )
        status = "accepted" if decision.accepted else "rejected"
        return web.json_response(
            {"status": status, "reason": decision.reason},
            status=200 if decision.accepted else 400,
        )
    except (ValueError, TypeError) as err:  # malformed query params
        return _json_error(err, 400)
    except Exception as err:  # noqa: BLE001 — HTTP boundary
        return _json_error(err, _status_for(err))


async def mc_processes(request: web.Request) -> web.Response:
    """Hosted FL processes with cycle progress — feeds the dashboard's
    FL section (no reference analog; its dashboard lists only
    data-centric models)."""
    ctx = _ctx(request)
    try:
        out = []
        for process in ctx.fl.process_manager.get():
            entry = {
                "name": process.name,
                "version": process.version,
                "cycles_completed": ctx.fl.cycle_manager.count_cycles(
                    fl_process_id=process.id, is_completed=True
                ),
                "cycles_total": ctx.fl.cycle_manager.count_cycles(
                    fl_process_id=process.id
                ),
            }
            # latest aggregated metrics embedded so the dashboard poll is
            # one request, not one per process per refresh
            latest = ctx.fl.cycle_manager.latest_metrics(process.id)
            if latest:
                entry["latest_metrics"] = latest
            out.append(entry)
        return web.json_response({"processes": out})
    except Exception as err:  # noqa: BLE001 — HTTP boundary
        return _json_error(err, _status_for(err))


async def mc_cycle_metrics(request: web.Request) -> web.Response:
    """Per-cycle sample-weighted training metrics reported by workers
    (this framework's extension — the reference has no structured
    metrics, SURVEY §5.5; `/metrics` is the Prometheus exposition, this
    is the FL-semantic curve)."""
    ctx = _ctx(request)
    try:
        filters: dict[str, Any] = {"name": request.query.get("name")}
        if request.query.get("version"):
            filters["version"] = request.query.get("version")
        process = ctx.fl.process_manager.first(**filters)
        return web.json_response(
            {"cycles": ctx.fl.cycle_manager.cycle_metrics(process.id)}
        )
    except Exception as err:  # noqa: BLE001 — HTTP boundary
        return _json_error(err, _status_for(err))


async def mc_retrieve_model(request: web.Request) -> web.Response:
    """Public checkpoint download by name/version/checkpoint alias or number
    (reference routes.py:471-516)."""
    ctx = _ctx(request)
    try:
        filters: dict[str, Any] = {"name": request.query.get("name")}
        if request.query.get("version"):
            filters["version"] = request.query.get("version")
        process = ctx.fl.process_manager.first(**filters)
        model = ctx.fl.model_manager.get(fl_process_id=process.id)
        checkpoint_query: dict[str, Any] = {"model_id": model.id}
        checkpoint = request.query.get("checkpoint")
        if checkpoint:
            if checkpoint.isnumeric():
                checkpoint_query["number"] = int(checkpoint)
            else:
                checkpoint_query["alias"] = checkpoint
        else:
            checkpoint_query["alias"] = "latest"
        record = ctx.fl.model_manager.load(**checkpoint_query)
        return web.Response(
            body=record.value, content_type="application/octet-stream"
        )
    except Exception as err:  # noqa: BLE001 — HTTP boundary
        return _json_error(err, _status_for(err))


# ── telemetry ────────────────────────────────────────────────────────────────


async def telemetry_cycles(request: web.Request) -> web.Response:
    """Newest-first summaries of recent FL cycles (phase durations,
    report counts, stragglers) — the dashboard's poll and the operator's
    index into the per-cycle detail route."""
    ctx = _ctx(request)
    try:
        limit = int(request.query.get("limit", 20))
    except ValueError as err:
        return _json_error(err, 400)
    return web.json_response(
        {"cycles": ctx.fl.cycle_manager.recent_cycles(max(1, limit))}
    )


async def telemetry_cycle_detail(request: web.Request) -> web.Response:
    """One cycle's full round timeline: per-phase durations, per-worker
    report latency/bytes/codec, wire bytes per codec, the trace ids that
    stitch it to client spans, and straggler counts."""
    ctx = _ctx(request)
    try:
        cycle_id = int(request.match_info["id"])
    except ValueError as err:
        return _json_error(err, 400)
    snap = ctx.fl.cycle_manager.cycle_timeline(cycle_id)
    if snap is None:
        return web.json_response(
            {"error": f"unknown cycle {cycle_id}"}, status=404
        )
    return web.json_response(snap)


async def telemetry_events(request: web.Request) -> web.Response:
    """The ring buffer's most recent structured events (spans included) —
    the low-tech trace viewer: filter by ?event= and ?trace_id=."""
    try:
        limit = int(request.query.get("limit", 200))
    except ValueError as err:
        return _json_error(err, 400)
    # filter BEFORE the tail-limit: a trace's spans must be findable even
    # when newer unrelated events have pushed them past `limit`
    events = telemetry.events(event=request.query.get("event"))
    trace_id = request.query.get("trace_id")
    if trace_id:
        events = [e for e in events if e.get("trace_id") == trace_id]
    return web.json_response(
        {"events": events[-max(1, min(limit, 2048)):]}
    )


# ── data-centric ─────────────────────────────────────────────────────────────


def _dc_session(request: web.Request):
    ctx = _ctx(request)
    token = request.headers.get("token") or request.query.get("token")
    session = ctx.sessions.by_token(token)
    if session is None:
        raise E.AuthorizationError("authentication required")
    return session


async def dc_models(request: web.Request) -> web.Response:
    """(reference routes.py: /models/) public list of hosted model ids."""
    ctx = _ctx(request)
    return web.json_response(
        {"success": True, "models": ctx.models.models(ctx.local_worker.id)}
    )


async def dc_detailed_models(request: web.Request) -> web.Response:
    ctx = _ctx(request)
    out = []
    for model_id in ctx.models.models(ctx.local_worker.id):
        hosted = ctx.models.get(ctx.local_worker.id, model_id)
        out.append(hosted.flags())
    return web.json_response({"success": True, "models": out})


async def dc_identity(request: web.Request) -> web.Response:
    return web.json_response(
        {"identity": _ctx(request).id, "version": __version__}
    )


async def dc_status(request: web.Request) -> web.Response:
    import os

    from pygrid_tpu.utils.profiling import stats

    # failpoint (pygrid_tpu/storm slow_node fault): the monitor's HTTP
    # heartbeat fallback lands here, so an injected delay is seen by the
    # network as real RTT degradation — 0.0 in production
    delay = getattr(_ctx(request), "chaos_status_delay_s", 0.0)
    if delay:
        await asyncio.sleep(delay)
    return web.json_response(
        {
            "status": "OK",
            "timings": stats.snapshot(),
            # self-reported placement (reference resolves this via geo-IP,
            # worker.py:47-61; zero-egress deployments set NODE_LOCATION)
            "location": os.environ.get("NODE_LOCATION"),
        }
    )


async def metrics(request: web.Request) -> web.Response:
    """Prometheus text exposition of the node's state and timings — beyond
    parity: the reference has no structured metrics at all (SURVEY §5.5,
    its observability is the 15s monitor JSON). Scrape ``/metrics``."""
    ctx = _ctx(request)
    from pygrid_tpu.utils.metrics import Exposition
    from pygrid_tpu.utils.profiling import stats

    exp = Exposition()
    fl = ctx.fl
    exp.counter("workers_total", fl.worker_manager.count(),
                "FL workers ever registered")
    exp.gauge("fl_processes", fl.process_manager.count(),
              "hosted FL processes")
    exp.counter("cycles_total", fl.cycle_manager.count_cycles(),
                "cycles created")
    exp.gauge(
        "cycles_open",
        fl.cycle_manager.count_cycles(is_completed=False),
        "cycles awaiting diffs",
    )
    exp.counter(
        "worker_diffs_total",
        fl.cycle_manager.count_worker_cycles(is_completed=True),
        "diffs received",
    )
    exp.gauge("hosted_models", len(ctx.models.models(ctx.local_worker.id)),
              "data-centric hosted models")
    exp.gauge("store_objects", sum(len(s) for s in ctx.all_stores()),
              "objects across tensor stores")
    for name, rec in stats.snapshot().items():
        labels = {"name": name}
        exp.counter("timing_seconds_total", rec["total_s"],
                    "cumulative seconds per timed section", labels)
        exp.counter("timing_invocations_total", rec["count"],
                    "invocations per timed section", labels)
    # serving engines: point-in-time gauges (the counters/histograms —
    # TTFT, per-token latency, occupancy, compiles — ride the bus below)
    for eng in ctx.serving.stats():
        labels = {"model": eng["model_id"]}
        exp.gauge("serving_queue_depth", eng["queue_depth"],
                  "generation rows waiting for a slot", labels)
        exp.gauge("serving_live_slots", eng["live_slots"],
                  "generation slots decoding right now", labels)
        exp.gauge("serving_max_slots", eng["max_slots"],
                  "generation slots in the shared KV cache", labels)
        if eng.get("paged"):
            # block-pool occupancy: free / used (held by live requests,
            # INCLUDING cached blocks they share) / cached (reclaimable
            # cache-only) — the three sum to the pool, and peak
            # shared-prefix load reads as USED, not as cache bloat
            total = eng["kv_blocks_total"]
            free = eng["kv_blocks_free"]
            idle_cached = eng["kv_blocks_idle_cached"]
            for state, value in (
                ("free", free),
                ("used", max(0, total - free - idle_cached)),
                ("cached", idle_cached),
            ):
                exp.gauge(
                    "serving_kv_blocks", value,
                    "paged KV pool blocks, by state (free/used/cached)",
                    {**labels, "state": state},
                )
            exp.gauge(
                "serving_kv_block_tokens", eng["block_size"],
                "tokens per paged KV block", labels,
            )
            exp.gauge(
                "serving_kv_fragmentation", eng["kv_fragmentation"],
                "allocated-but-unwritten fraction of live KV pages",
                labels,
            )
    # the telemetry bus: event counters + every histogram family
    # (request latency by route, frame decode time, report latency,
    # cycle phases, wire bytes by codec, serde tensor copies)
    telemetry.export(exp)
    # device-memory gauges (background-sampled; absent on CPU backends)
    # and the SLO compliance/burn gauges
    telemetry.profiler.export_device_memory(exp)
    ctx.slo.export(exp)
    return web.Response(
        text=exp.render(), content_type="text/plain", charset="utf-8"
    )


async def dc_workers(request: web.Request) -> web.Response:
    ctx = _ctx(request)
    workers = [w.id for w in ctx.fl.worker_manager.query()]
    return web.json_response({"workers": workers})


async def dc_download_model(request: web.Request) -> web.Response:
    """GET twin of serve-model: the hosted blob back out, gated on the
    model's ``allow_download`` flag and a session token (the flag the
    reference's ModelStorage carries for exactly this purpose)."""
    ctx = _ctx(request)
    try:
        _dc_session(request)
        model_id = _require_query(request, "model_id")[0]
        hosted = ctx.models.get(ctx.local_worker.id, model_id)
        if not hosted.allow_download:
            raise E.AuthorizationError(
                "You're not allowed to download this model."
            )
        from pygrid_tpu.serde import serialize

        blob = hosted.serialized
        if blob is None:
            # serializing a model-scale payload on the event loop would
            # stall every other socket (gridlint GL303)
            blob = await _off_loop(serialize, hosted.model)
        return web.Response(
            body=blob, content_type="application/octet-stream"
        )
    except Exception as err:  # noqa: BLE001 — HTTP boundary
        return _json_error(err, _status_for(err))


async def dc_serve_model(request: web.Request) -> web.Response:
    """(reference routes.py:128-169) host a model over HTTP; multipart for
    big payloads or JSON with base64 body."""
    ctx = _ctx(request)
    try:
        # the cheap session gate FIRST: an anonymous caller must not
        # burn executor CPU decoding a multi-megabyte body
        _dc_session(request)

        def _save(fields: dict, blob: bytes):
            return ctx.models.save(
                ctx.local_worker.id,
                blob,
                fields.get("model_id"),
                allow_download=str(fields.get("allow_download")) == "True",
                allow_remote_inference=str(
                    fields.get("allow_remote_inference")
                )
                == "True",
                mpc=str(fields.get("mpc")) == "True",
            )

        if request.content_type.startswith("multipart/"):
            reader = await request.multipart()
            fields: dict[str, Any] = {}
            async for part in reader:
                if part.name == "model":
                    fields["model"] = await part.read(decode=False)
                else:
                    fields[part.name] = (await part.text())
            blob = bytes(fields.pop("model"))
            result = await _off_loop(_save, fields, blob)
        else:
            # JSON parse of the megabyte body, base64 decode of its
            # model field and the persist are all milliseconds-per-
            # megabyte of CPU (gridlint GL303) — ONE executor hop for
            # the lot, not three round-trips
            text = await request.text()

            def _decode_and_save():
                fields = json.loads(text)
                blob = base64.b64decode(fields.pop("model"))
                return _save(fields, blob)

            result = await _off_loop(_decode_and_save)
        return web.json_response(result)
    except Exception as err:  # noqa: BLE001 — HTTP boundary
        return _json_error(err, _status_for(err))


async def dc_run_generation(request: web.Request) -> web.Response:
    """HTTP door into the continuous-batching generation engine
    (docs/SERVING.md) — a genuinely async enqueue-and-await: the
    request's rows join the model's batch and the event loop awaits the
    engine future directly, so a slow generation holds no executor
    thread at all. Body mirrors the WS ``run-generation`` event
    (``model_id``, base64 ``data``, ``n_new``, ``temperature``,
    ``seed``); session token via the ``token`` header. A full queue is
    503, validation defects are 400 — same typed messages as the WS
    twin (both doors share ``_prepare_generation``)."""
    import asyncio

    from pygrid_tpu.node.events import _prepare_generation

    ctx = _ctx(request)
    try:
        _dc_session(request)
        body = json.loads(await request.text())
        # validation deserializes the (possibly large) prompt blob —
        # off the event loop like every other blocking handler
        prep = await _off_loop(_prepare_generation, ctx, body)
        if isinstance(prep, dict):
            return web.json_response(prep, status=400)
        hosted, prompt, n_new, temperature, seed = prep
        engine = ctx.serving.engine_for(
            str(body[MSG_FIELD.MODEL_ID]), hosted
        )
        future = engine.enqueue(prompt, n_new, temperature, seed)
        tokens = await asyncio.wait_for(
            asyncio.wrap_future(future),
            timeout=engine.config.default_timeout_s,
        )
        return web.json_response(
            {"success": True, "tokens": tokens.tolist()}
        )
    except asyncio.TimeoutError:
        return _json_error(
            E.PyGridError("generation timed out awaiting the batch engine"),
            504,
        )
    except (json.JSONDecodeError, ValueError, TypeError) as err:
        # same client-defect class the WS door answers typed (e.g.
        # n_new="abc", undecodable data blob) — a 400, never a 500
        return _json_error(err, 400)
    except Exception as err:  # noqa: BLE001 — HTTP boundary
        return _json_error(err, _status_for(err))


async def telemetry_serving(request: web.Request) -> web.Response:
    """Per-engine serving gauges (queue depth, live slots, totals) —
    the dashboard's poll; histograms (TTFT, per-token latency, batch
    occupancy) are on /metrics."""
    return web.json_response({"engines": _ctx(request).serving.stats()})


async def telemetry_programs(request: web.Request) -> web.Response:
    """Compile-cache introspection: every jitted serving program's key,
    bucket, compile ms, hit count AND its XLA cost analysis (flops /
    bytes accessed from ``jax.stages`` — rows ranked by total bytes
    accessed, i.e. device pressure, not just wall-clock), plus the
    latest device-memory sample. The cost pass re-lowers each program
    once from captured avals; ``?cost=0`` (or PYGRID_PROFILER_COST=off)
    skips it. The first costed snapshot runs off the event loop — a
    lower/compile must not stall the sockets."""
    include_cost = request.query.get("cost", "1") not in ("0", "false")
    if include_cost:
        programs = await _off_loop(
            lambda: telemetry.profiler.programs_snapshot(include_cost=True)
        )
    else:
        programs = telemetry.profiler.programs_snapshot()
    return web.json_response(
        {
            "programs": programs,
            "device_memory": telemetry.profiler.MEMORY.latest(),
            "device_memory_age_s": telemetry.profiler.MEMORY.age_s(),
            "profiler_enabled": telemetry.profiler.enabled(),
            "cost_enabled": telemetry.profiler.cost_enabled(),
        }
    )


async def telemetry_slo(request: web.Request) -> web.Response:
    """Burn-rate SLO evaluation (telemetry/slo.py): per objective the
    compliance, per-window burn rates, and ok/warn/breach status — the
    dashboard SLO table and any alerting glue poll this."""
    return web.json_response({"slo": _ctx(request).slo.evaluate()})


async def telemetry_dump(request: web.Request) -> web.Response:
    """Operator-triggered flight-recorder crash dump: writes the
    redacted JSON black box (ring + bus events + engine snapshots) and
    returns its path. Session-token gated (a dump is work + disk, and
    crash evidence must not be evictable by anonymous callers); always
    writes once authorized (bypasses the per-reason rate limit); the
    file write runs off the event loop."""
    ctx = _ctx(request)
    try:
        _dc_session(request)
    except Exception as err:  # noqa: BLE001 — HTTP boundary
        return _json_error(err, _status_for(err))
    path = await _off_loop(
        lambda: telemetry.recorder.dump(
            "operator", snapshot={"serving": ctx.serving.stats()},
            force=True,
        )
    )
    return web.json_response({"success": True, "path": path})


async def healthz(request: web.Request) -> web.Response:
    """Shallow by default (the process answers → 200, for LB probes);
    ``?deep=1`` evaluates the SLO engine and serving state and answers
    503 when any objective is in breach — the page-someone signal."""
    if request.query.get("deep") not in ("1", "true", "yes"):
        return web.json_response({"status": "ok"})
    ctx = _ctx(request)
    rows = ctx.slo.evaluate()
    breaches = [r["name"] for r in rows if r["status"] == "breach"]
    body = {
        "status": "breach" if breaches else "ok",
        "breaches": breaches,
        "slo": rows,
        "serving": ctx.serving.stats(),
    }
    return web.json_response(body, status=503 if breaches else 200)


async def dc_dataset_tags(request: web.Request) -> web.Response:
    """(reference routes.py:171-189) all tags across the node's store."""
    ctx = _ctx(request)
    tags: set[str] = set()
    for store in ctx.all_stores():
        tags |= store.tags()
    return web.json_response(sorted(tags))


def _find_shared_tensors(value: Any) -> list[Any]:
    """Descend a hosted model / plan state to its shared tensors — live
    AdditiveSharingTensors or SharedTensorRef wiring metadata; both carry
    ``owners``/``crypto_provider_id``. (Reference routes.py:192-250 walks
    Plan.state tensor chains the same way.)"""
    found = []
    if hasattr(value, "owners") and hasattr(value, "crypto_provider_id"):
        found.append(value)
    elif isinstance(value, Plan) and value.state is not None:
        for t in value.state.tensors():
            found.extend(_find_shared_tensors(t))
    elif isinstance(value, (list, tuple)):
        for v in value:
            found.extend(_find_shared_tensors(v))
    return found


async def dc_search_encrypted_models(request: web.Request) -> web.Response:
    ctx = _ctx(request)
    try:
        body = json.loads(await request.text())
        model_id = body.get("model_id")
        hosted = ctx.models.get(ctx.local_worker.id, model_id)
        if not hosted.mpc:
            raise E.ModelNotFoundError()
        shared = _find_shared_tensors(hosted.model)
        if not shared:
            raise E.ModelNotFoundError()
        workers = sorted({o for t in shared for o in t.owners})
        providers = sorted(
            {
                t.crypto_provider_id
                for t in shared
                if t.crypto_provider_id is not None
            }
        )
        return web.json_response(
            {
                "success": True,
                "workers": workers,
                "crypto_provider": providers,
            }
        )
    except Exception as err:  # noqa: BLE001 — HTTP boundary
        return _json_error(err, _status_for(err))


async def dc_search(request: web.Request) -> web.Response:
    """(reference routes.py:253-273) tag search over the node's store."""
    ctx = _ctx(request)
    try:
        body = json.loads(await request.text())
        query = body.get("query") or []
        found = [o for store in ctx.all_stores() for o in store.search(query)]
        return web.json_response(
            {"content": bool(found), "count": len(found)}
        )
    except Exception as err:  # noqa: BLE001 — HTTP boundary
        return _json_error(err, _status_for(err))


# ── users HTTP CRUD (reference routes/{user,role,group}_related.py) ──────────


def _ws_twin(event_type: str):
    async def handler(request: web.Request) -> web.Response:
        ctx = _ctx(request)
        try:
            data = json.loads(await request.text()) if request.can_read_body else {}
        except json.JSONDecodeError as err:
            return _json_error(err, 400)
        token = request.headers.get("token")
        if token and "token" not in data:
            data["token"] = token
        data.update(
            {k: v for k, v in request.match_info.items() if k not in data}
        )
        response = _USER_HANDLERS[event_type](
            ctx, {MSG_FIELD.DATA: data}, Connection(ctx)
        )
        status = 200 if "error" not in response else 400
        return web.json_response(response, status=status)

    return handler


# ── registration ─────────────────────────────────────────────────────────────


def register(app: web.Application) -> None:
    r = app.router
    # model-centric (reference blueprint /model-centric)
    r.add_post("/model-centric/cycle-request", mc_cycle_request)
    r.add_route("*", "/model-centric/speed-test", mc_speed_test)
    r.add_post("/model-centric/report", mc_report)
    r.add_post("/model-centric/authenticate", mc_authenticate)
    r.add_get("/model-centric/get-model", mc_get_model)
    r.add_get("/model-centric/get-plan", mc_get_plan)
    r.add_get("/model-centric/get-protocol", mc_get_protocol)
    r.add_get("/model-centric/req-join", mc_req_join)
    r.add_get("/model-centric/retrieve-model", mc_retrieve_model)
    r.add_get("/model-centric/cycle-metrics", mc_cycle_metrics)
    r.add_get("/model-centric/processes", mc_processes)
    # data-centric (reference blueprint /data-centric)
    r.add_get("/data-centric/models/", dc_models)
    r.add_get("/data-centric/detailed-models-list/", dc_detailed_models)
    r.add_get("/data-centric/identity/", dc_identity)
    r.add_get("/metrics", metrics)
    # telemetry (no reference analog — SURVEY §5.1: stdlib logging only)
    r.add_get("/telemetry/cycles", telemetry_cycles)
    r.add_get("/telemetry/cycles/{id}", telemetry_cycle_detail)
    r.add_get("/telemetry/events", telemetry_events)
    r.add_get("/telemetry/serving", telemetry_serving)
    r.add_get("/telemetry/programs", telemetry_programs)
    r.add_get("/telemetry/slo", telemetry_slo)
    r.add_post("/telemetry/dump", telemetry_dump)
    r.add_get("/healthz", healthz)
    r.add_post("/data-centric/run-generation", dc_run_generation)
    r.add_get("/data-centric/status/", dc_status)
    r.add_get("/data-centric/workers/", dc_workers)
    r.add_post("/data-centric/serve-model/", dc_serve_model)
    r.add_get("/data-centric/serve-model/", dc_download_model)
    r.add_get("/data-centric/dataset-tags", dc_dataset_tags)
    r.add_post("/data-centric/search-encrypted-models", dc_search_encrypted_models)
    r.add_post("/data-centric/search", dc_search)
    # users
    from pygrid_tpu.utils.codes import GROUP_EVENTS, ROLE_EVENTS, USER_EVENTS

    r.add_post("/users/signup", _ws_twin(USER_EVENTS.SIGNUP_USER))
    r.add_post("/users/login", _ws_twin(USER_EVENTS.LOGIN_USER))
    r.add_get("/users/", _ws_twin(USER_EVENTS.GET_ALL_USERS))
    r.add_get("/users/{id}", _ws_twin(USER_EVENTS.GET_SPECIFIC_USER))
    r.add_post("/users/search", _ws_twin(USER_EVENTS.SEARCH_USERS))
    r.add_put("/users/{id}/email", _ws_twin(USER_EVENTS.PUT_EMAIL))
    r.add_put("/users/{id}/password", _ws_twin(USER_EVENTS.PUT_PASSWORD))
    r.add_put("/users/{id}/role", _ws_twin(USER_EVENTS.PUT_ROLE))
    r.add_put("/users/{id}/groups", _ws_twin(USER_EVENTS.PUT_GROUPS))
    r.add_delete("/users/{id}", _ws_twin(USER_EVENTS.DELETE_USER))
    r.add_post("/roles/", _ws_twin(ROLE_EVENTS.CREATE_ROLE))
    r.add_get("/roles/", _ws_twin(ROLE_EVENTS.GET_ALL_ROLES))
    r.add_get("/roles/{id}", _ws_twin(ROLE_EVENTS.GET_ROLE))
    r.add_put("/roles/{id}", _ws_twin(ROLE_EVENTS.PUT_ROLE))
    r.add_delete("/roles/{id}", _ws_twin(ROLE_EVENTS.DELETE_ROLE))
    r.add_post("/groups/", _ws_twin(GROUP_EVENTS.CREATE_GROUP))
    r.add_get("/groups/", _ws_twin(GROUP_EVENTS.GET_ALL_GROUPS))
    r.add_get("/groups/{id}", _ws_twin(GROUP_EVENTS.GET_GROUP))
    r.add_put("/groups/{id}", _ws_twin(GROUP_EVENTS.PUT_GROUP))
    r.add_delete("/groups/{id}", _ws_twin(GROUP_EVENTS.DELETE_GROUP))
