"""Node web dashboard.

Parity surface: reference ``apps/node/src/app/{templates/index.html,
static/js/main.js}`` — a landing page that fetches
``/data-centric/detailed-models-list/`` and renders the hosted models.
Here it is one self-contained page (no static asset tree) that also shows
node identity/status, so a browser hitting the node root sees the grid
state. All dynamic values — the node id and every model field — render
through HTML escaping / ``textContent``, never markup interpolation: a
hosted model id is client-supplied data and must not execute in the
operator's browser.
"""

from __future__ import annotations

import html

PAGE = """<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>pygrid-tpu node — {node_id}</title>
<style>
  body {{ font-family: system-ui, sans-serif; margin: 2rem auto; max-width: 52rem;
         color: #1a1a1a; }}
  h1 {{ font-size: 1.4rem; }} code {{ background: #f4f4f4; padding: .1em .3em; }}
  table {{ border-collapse: collapse; width: 100%; margin-top: 1rem; }}
  th, td {{ text-align: left; padding: .4rem .6rem; border-bottom: 1px solid #ddd; }}
  .muted {{ color: #777; }}
</style>
</head>
<body>
<h1>pygrid-tpu node <code>{node_id}</code></h1>
<p class="muted" id="status">loading status…</p>
<h2>FL processes</h2>
<table id="fl"><thead>
<tr><th>name</th><th>version</th><th>cycles</th><th>latest loss</th>
<th>latest acc</th></tr>
</thead><tbody></tbody></table>
<h2>Hosted models</h2>
<table id="models"><thead>
<tr><th>id</th><th>download</th><th>remote inference</th><th>mpc</th></tr>
</thead><tbody></tbody></table>
<h2>Recent cycles</h2>
<table id="cycles"><thead>
<tr><th>cycle</th><th>seq</th><th>reports</th><th>stragglers</th>
<th>aggregate (ms)</th><th>outcome</th></tr>
</thead><tbody></tbody></table>
<h2>Generation serving</h2>
<table id="serving"><thead>
<tr><th>model</th><th>queue</th><th>slots live</th><th>requests</th>
<th>tokens</th><th>compiles</th></tr>
</thead><tbody></tbody></table>
<h2>SLOs</h2>
<table id="slo"><thead>
<tr><th>objective</th><th>target</th><th>events</th><th>compliance</th>
<th>burn (short)</th><th>burn (long)</th><th>status</th></tr>
</thead><tbody></tbody></table>
<script>
function row(fields) {{
  const tr = document.createElement('tr');
  for (const value of fields) {{
    const td = document.createElement('td');
    td.textContent = String(value);  // data, never markup
    tr.appendChild(td);
  }}
  return tr;
}}
async function refresh() {{
  try {{
    const st = await (await fetch('/data-centric/status/')).json();
    document.getElementById('status').textContent =
      'status: ' + (st.status || JSON.stringify(st));
    const res = await (await fetch('/data-centric/detailed-models-list/')).json();
    const tbody = document.querySelector('#models tbody');
    tbody.replaceChildren();
    const models = res.models || [];
    if (!models.length) {{
      const tr = document.createElement('tr');
      const td = document.createElement('td');
      td.colSpan = 4; td.className = 'muted'; td.textContent = 'none';
      tr.appendChild(td); tbody.appendChild(tr);
    }}
    for (const m of models) {{
      tbody.appendChild(
        row([m.id, m.allow_download, m.allow_remote_inference, m.mpc]));
    }}
    const fl = await (await fetch('/model-centric/processes')).json();
    const flBody = document.querySelector('#fl tbody');
    flBody.replaceChildren();
    const procs = fl.processes || [];
    if (!procs.length) {{
      const tr = document.createElement('tr');
      const td = document.createElement('td');
      td.colSpan = 5; td.className = 'muted'; td.textContent = 'none';
      tr.appendChild(td); flBody.appendChild(tr);
    }}
    for (const p of procs) {{
      const m = p.latest_metrics || {{}};
      flBody.appendChild(row([
        p.name, p.version,
        p.cycles_completed + '/' + p.cycles_total,
        'loss' in m ? m.loss.toFixed(4) : '—',
        'acc' in m ? m.acc.toFixed(4) : '—']));
    }}
    const tl = await (await fetch('/telemetry/cycles')).json();
    const cyBody = document.querySelector('#cycles tbody');
    cyBody.replaceChildren();
    const cycles = tl.cycles || [];
    if (!cycles.length) {{
      const tr = document.createElement('tr');
      const td = document.createElement('td');
      td.colSpan = 6; td.className = 'muted'; td.textContent = 'none';
      tr.appendChild(td); cyBody.appendChild(tr);
    }}
    for (const c of cycles) {{
      const agg = (c.phases || {{}}).aggregate;
      cyBody.appendChild(row([
        c.cycle_id, c.sequence ?? '—',
        c.reported + '/' + c.assigned,
        c.stragglers ?? '—',
        agg !== undefined ? (agg * 1000).toFixed(1) : '—',
        c.outcome || 'open']));
    }}
    const sv = await (await fetch('/telemetry/serving')).json();
    const svBody = document.querySelector('#serving tbody');
    svBody.replaceChildren();
    const engines = sv.engines || [];
    if (!engines.length) {{
      const tr = document.createElement('tr');
      const td = document.createElement('td');
      td.colSpan = 6; td.className = 'muted'; td.textContent = 'none';
      tr.appendChild(td); svBody.appendChild(tr);
    }}
    for (const e of engines) {{
      svBody.appendChild(row([
        e.model_id, e.queue_depth,
        e.live_slots + '/' + e.max_slots,
        e.requests_total, e.tokens_total, e.compiles_total]));
    }}
    const slo = await (await fetch('/telemetry/slo')).json();
    const sloBody = document.querySelector('#slo tbody');
    sloBody.replaceChildren();
    const objectives = slo.slo || [];
    if (!objectives.length) {{
      const tr = document.createElement('tr');
      const td = document.createElement('td');
      td.colSpan = 7; td.className = 'muted'; td.textContent = 'none';
      tr.appendChild(td); sloBody.appendChild(tr);
    }}
    for (const o of objectives) {{
      const burns = Object.values(o.burn || {{}});
      const fmt = (v) => v === null || v === undefined
        ? '—' : Number(v).toFixed(2);
      sloBody.appendChild(row([
        o.name, o.target, o.events,
        o.compliance === null ? '—' : (o.compliance * 100).toFixed(1) + '%',
        fmt(burns[0]), fmt(burns[1]), o.status]));
    }}
  }} catch (err) {{
    document.getElementById('status').textContent = 'error: ' + err;
  }}
}}
refresh(); setInterval(refresh, 5000);
</script>
</body>
</html>
"""


def render(node_id: str) -> str:
    return PAGE.format(node_id=html.escape(str(node_id), quote=True))
