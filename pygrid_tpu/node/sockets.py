"""Socket ↔ worker-id registry.

Parity surface: reference ``events/socket_handler.py:13-63`` — a singleton
mapping worker ids to live sockets so FL events can push to a specific
worker, and so a dropped socket unregisters its worker. Here one instance
per app (no module singleton), keyed by the aiohttp WebSocketResponse.
"""

from __future__ import annotations

from typing import Any


class SocketHandler:
    def __init__(self) -> None:
        self._by_worker: dict[str, Any] = {}
        self._by_socket: dict[int, str] = {}

    def new_connection(self, worker_id: str, socket: Any) -> None:
        self._by_worker[worker_id] = socket
        if socket is not None:
            self._by_socket[id(socket)] = worker_id

    def socket_of(self, worker_id: str) -> Any | None:
        return self._by_worker.get(worker_id)

    def worker_of(self, socket: Any) -> str | None:
        return self._by_socket.get(id(socket))

    def remove(self, socket: Any) -> str | None:
        """Unregister the worker bound to this socket (fixes the reference's
        return-inside-loop bug, socket_handler.py:43-55 — noted SURVEY §5.2)."""
        worker_id = self._by_socket.pop(id(socket), None)
        if worker_id is not None:
            self._by_worker.pop(worker_id, None)
        return worker_id

    def __len__(self) -> int:
        return len(self._by_worker)
