"""Node app — the coordination-plane server.

Parity surface: reference ``apps/node/src/app/__init__.py`` (create_app:131,
seed_db:79, blueprints /, /model-centric, /data-centric + WS at
``:173-178``) and ``apps/node/src/__main__.py`` (CLI + network join + server).
The reference serves gevent WSGI + Flask-Sockets; here it is one asyncio
aiohttp application carrying HTTP routes and the WebSocket endpoint.

``NodeContext`` is the app-wide singleton the reference scatters across
module globals (local_worker, model_controller, session repo, FLController):
one object, explicitly threaded through handlers.
"""

from __future__ import annotations

import secrets

from pygrid_tpu.datacentric import (
    KVStore,
    MemoryKV,
    ModelController,
    SessionsRepository,
    SqliteKV,
    set_persistent_mode,
)
from pygrid_tpu.federated.controller import FLController
from pygrid_tpu.runtime.worker import VirtualWorker
from pygrid_tpu.storage.warehouse import Database
from pygrid_tpu.users import UserManager

__version__ = "0.1.0"


class NodeContext:
    """Everything one Node owns (reference main/__init__.py:8-17 globals +
    app factory wiring)."""

    def __init__(
        self,
        node_id: str,
        database_url: str = ":memory:",
        kv: KVStore | None = None,
        kv_path: str | None = None,
        secret_key: str | None = None,
        network_url: str | None = None,
        num_replicas: int | None = None,
        strict_crypto_store: bool = False,
    ) -> None:
        self.id = node_id
        self.address: str | None = None
        self.network_url = network_url
        self.num_replicas = num_replicas
        self.db = Database(database_url)
        self.kv: KVStore = (
            kv
            if kv is not None
            else (SqliteKV(kv_path) if kv_path else MemoryKV())
        )
        self.secret_key = secret_key or secrets.token_hex(16)

        # the Node's singleton party (reference local_worker)
        self.local_worker = VirtualWorker(id=node_id)
        set_persistent_mode(self.local_worker, self.kv)
        # every node can act as a cross-node triple dealer (the reference's
        # crypto-provider worker, e.g. james in
        # test_basic_syft_operations.py:455-491); strict mode reproduces
        # the EmptyCryptoPrimitiveStoreError refill round-trip
        from pygrid_tpu.smpc.provider import CryptoProvider

        self.crypto_provider = CryptoProvider(
            id=f"{node_id}-crypto", strict_store=strict_crypto_store
        )
        self.local_worker.crypto_provider = self.crypto_provider

        self.fl = FLController(self.db)
        # a restarted node resumes mid-process from SQL (reference posture,
        # SURVEY §5.4); deadlined open cycles need their timers re-armed,
        # and secagg cycles whose in-memory key rounds died close
        # explicitly so clients re-key instead of polling a dead round
        self.fl.cycle_manager.recover_deadlines()
        self.fl.cycle_manager.recover_secagg()
        self.models = ModelController(self.kv)
        self.sessions = SessionsRepository()
        self.users = UserManager(self.db, secret_key=self.secret_key)
        # continuous-batching generation engines, one per hosted
        # transformer bundle (pygrid_tpu/serving, docs/SERVING.md) —
        # cheap to construct (engines build lazily on first request);
        # slot/queue depth are the ops sizing knobs
        import os

        from pygrid_tpu.serving import EngineConfig, ServingManager

        self.serving = ServingManager(
            EngineConfig(
                max_slots=int(os.environ.get("PYGRID_SERVING_SLOTS", "8")),
                max_queue=int(os.environ.get("PYGRID_SERVING_QUEUE", "64")),
            )
        )
        # burn-rate SLOs over the bus histograms (telemetry/slo.py):
        # GET /telemetry/slo, the deep /healthz, and the dashboard table
        from pygrid_tpu.telemetry.slo import SLOEngine, node_objectives

        self.slo = SLOEngine(node_objectives())
        #: failpoint (pygrid_tpu/storm slow_node fault): seconds of
        #: artificial delay injected into the /data-centric/status/
        #: heartbeat — 0.0 (off) outside chaos drills
        self.chaos_status_delay_s = 0.0

    def all_stores(self):
        """The node's singleton store plus every live session worker's store —
        the scan surface for public discovery routes (/dataset-tags, /search),
        mirroring the reference's local_worker._objects scan
        (routes/data_centric/routes.py:171-189,253-273)."""
        stores = [self.local_worker.store]
        for session in self.sessions.all_sessions():
            if session._worker is not None:
                stores.append(session._worker.store)
        return stores


def create_app(
    node_id: str,
    database_url: str = ":memory:",
    kv_path: str | None = None,
    secret_key: str | None = None,
    network_url: str | None = None,
    num_replicas: int | None = None,
    strict_crypto_store: bool = False,
):
    """Build the aiohttp application (reference create_app, __init__.py:131)."""
    from aiohttp import web

    from pygrid_tpu.node import routes as R
    from pygrid_tpu.node.ws import ws_handler

    ctx = NodeContext(
        node_id,
        database_url=database_url,
        kv_path=kv_path,
        secret_key=secret_key,
        network_url=network_url,
        num_replicas=num_replicas,
        strict_crypto_store=strict_crypto_store,
    )
    from pygrid_tpu import telemetry

    app = web.Application(
        client_max_size=256 * 1024 * 1024,
        middlewares=[telemetry.http_middleware()],
    )
    app["node"] = ctx

    async def _close_serving(app):
        # stop the generation engines' worker threads with the app —
        # queued requests fail typed instead of hanging on a dead server
        app["node"].serving.close()

    app.on_cleanup.append(_close_serving)

    async def _start_observability(app):
        import asyncio
        import logging

        from pygrid_tpu.telemetry.bus import env_float

        # device-memory gauges sample on their own daemon thread;
        # the SLO engine snapshots on an asyncio cadence so burn-rate
        # windows have data even when no one scrapes. Clamped: 0 or a
        # negative knob would make the tick task a hot loop.
        telemetry.profiler.MEMORY.start()
        # periodic engine snapshots (flight recorder §7): crash dumps
        # carry a before-the-crash trajectory — cycle accumulators and
        # serving stats every ~10 s under load, nothing when idle
        telemetry.recorder.register_stats_provider(
            f"aggregation:{app['node'].id}", app["node"].fl.cycle_manager
        )
        telemetry.recorder.start_snapshots()
        interval = max(1.0, env_float("PYGRID_SLO_INTERVAL_S", 15.0))

        async def _tick():
            while True:
                await asyncio.sleep(interval)
                try:
                    # evaluate (not just tick): status transitions are
                    # detected here, so breach webhooks (§6) fire even
                    # when nobody is scraping /telemetry/slo — and the
                    # POST itself runs on the notifier's daemon thread,
                    # never this loop
                    app["node"].slo.evaluate()
                except Exception:  # noqa: BLE001 — cadence must survive
                    logging.getLogger(__name__).exception(
                        "SLO tick failed"
                    )

        app["slo_task"] = asyncio.get_running_loop().create_task(_tick())

    async def _stop_observability(app):
        import asyncio
        import contextlib

        task = app.get("slo_task")
        if task:
            task.cancel()
            # suppress the cancellation AND any stored exception: either
            # re-raising out of an on_cleanup hook would cancel the whole
            # app cleanup and skip the sampler release below
            # (CancelledError is a BaseException, not an Exception)
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await task
        # the sampler/snapshotter stop() joins their threads (possibly
        # mid-sample) — blocking waits that must not run on the event loop
        await asyncio.get_running_loop().run_in_executor(
            None, telemetry.profiler.MEMORY.stop
        )
        await asyncio.get_running_loop().run_in_executor(
            None, telemetry.recorder.stop_snapshots
        )

    app.on_startup.append(_start_observability)
    app.on_cleanup.append(_stop_observability)
    app.router.add_get("/", ws_handler)  # WS upgrade or landing JSON
    R.register(app)
    return app
