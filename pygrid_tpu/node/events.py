"""WS event handlers + dispatch table for the Node.

Parity surface: reference ``apps/node/src/app/main/events/`` — the routes
table (``events/__init__.py:23-57``), ``route_requests`` (JSON dispatch by
``type``; **binary frames → forward_binary_message**, ``:61-107``), the
model-centric FL events (``model_centric/fl_events.py``), the data-centric
syft/model/control events (``data_centric/*.py``), and the user/role/group WS
twins. Handlers are transport-agnostic: they take (ctx, message, conn) and
return a dict; the aiohttp WS endpoint (pygrid_tpu.node.ws) does the framing.
"""

from __future__ import annotations

import base64
import binascii
import logging
import os
import uuid
from typing import Any, Callable

from pygrid_tpu import telemetry
from pygrid_tpu.datacentric.object_storage import recover_objects
from pygrid_tpu.federated.auth import verify_token
from pygrid_tpu.node import NodeContext, __version__
from pygrid_tpu.telemetry import trace
from pygrid_tpu.node.sockets import SocketHandler
from pygrid_tpu.serde import deserialize, serialize
from pygrid_tpu.users.events import USER_HANDLERS
from pygrid_tpu.utils import exceptions as E
from pygrid_tpu.utils.codes import (
    CONTROL_EVENTS,
    CYCLE,
    MODEL_CENTRIC_FL_EVENTS,
    MSG_FIELD,
    REQUEST_MSG,
)

logger = logging.getLogger(__name__)

SUCCESS = "success"
ERROR = "error"


class Connection:
    """Per-WebSocket state: the data-centric login session and the FL
    worker id bound to this socket."""

    def __init__(self, ctx: NodeContext, socket: Any = None) -> None:
        self.ctx = ctx
        self.socket = socket
        self.session = None  # UserSession after `authentication`
        self.worker_id: str | None = None
        #: wire-v2 negotiation result (set by the WS endpoint after the
        #: subprotocol handshake); False/None on legacy connections and
        #: HTTP-route synthetic connections
        self.wire_v2: bool = False
        self.wire_codec: str | None = None
        #: True while dispatching a binary (msgpack) frame — handlers that
        #: return raw payload bytes (get-model) use it to pick base64 for
        #: the JSON framing
        self.binary_frame: bool = False
        #: one-shot hint from a handler to the WS endpoint: the response
        #: already embeds a pre-compressed payload (the per-checkpoint
        #: blob cache), so the per-frame codec pass would be K-per-round
        #: wasted work — skip it for THIS response only
        self.suppress_frame_codec: bool = False
        #: one-shot trace context extracted from a wire-v2 frame header
        #: by the WS endpoint (consumed by route_requests); and the span
        #: this connection served the current message under (set by
        #: route_requests, consumed by the WS endpoint for the response
        #: frame's trace header)
        self.incoming_trace = None
        self.last_trace = None

    @property
    def worker(self):
        if self.session is None:
            raise E.AuthorizationError("authentication required")
        return self.session.worker

    def codec_label(self) -> str:
        """The wire-codec label telemetry attributes this message's
        payload bytes to — one definition so the download and report
        counters can never disagree."""
        if self.binary_frame and self.wire_codec:
            return self.wire_codec
        return "binary" if self.binary_frame else "json"


# ── model-centric FL events (reference fl_events.py) ─────────────────────────


def _unhex(value: str | None) -> bytes | None:
    if value is None:
        return None
    return binascii.unhexlify(value.encode())


def host_federated_training(
    ctx: NodeContext, message: dict, conn: Connection
) -> dict:
    """(reference fl_events.py:27-75) deserialize hex model/plans/protocols/
    avg-plan and create the FLProcess + first cycle."""
    data = message.get(MSG_FIELD.DATA) or {}
    response: dict[str, Any] = {}
    try:
        model_blob = _unhex(data.get(MSG_FIELD.MODEL))
        client_plans = {
            k: _unhex(v) for k, v in (data.get(CYCLE.PLANS) or {}).items()
        }
        client_protocols = {
            k: _unhex(v) for k, v in (data.get(CYCLE.PROTOCOLS) or {}).items()
        }
        avg_plan = _unhex(data.get(CYCLE.AVG_PLAN))
        client_config = data.get(CYCLE.CLIENT_CONFIG) or {}
        server_config = data.get(CYCLE.SERVER_CONFIG) or {}
        ctx.fl.create_process(
            model_blob=model_blob,
            client_plans=client_plans,
            name=client_config.get("name", ""),
            version=client_config.get("version", ""),
            client_config=client_config,
            server_config=server_config,
            server_averaging_plan=avg_plan,
            client_protocols=client_protocols,
        )
        response[CYCLE.STATUS] = SUCCESS
    except Exception as err:  # noqa: BLE001 — protocol boundary
        logger.exception("host-training failed")
        response[ERROR] = str(err)
    return {
        MSG_FIELD.TYPE: MODEL_CENTRIC_FL_EVENTS.HOST_FL_TRAINING,
        MSG_FIELD.DATA: response,
    }


def requires_speed_test(ctx: NodeContext, name: str, version: str | None) -> bool:
    """(reference fl_events.py:112-128) true when the process sets bandwidth
    minimums."""
    filters = {"name": name}
    if version:
        filters["version"] = version
    process = ctx.fl.process_manager.first(**filters)
    server_config = ctx.fl.process_manager.get_configs(
        fl_process_id=process.id, is_server_config=True
    )
    return (
        server_config.get("minimum_upload_speed") is not None
        or server_config.get("minimum_download_speed") is not None
    )


def assign_worker_id(ctx: NodeContext, conn: Connection, handler: SocketHandler):
    """(reference fl_events.py:77-109) uuid4 worker id + socket binding."""
    worker_id = str(uuid.uuid4())
    handler.new_connection(worker_id, conn.socket)
    conn.worker_id = worker_id
    ctx.fl.worker_manager.create(worker_id)
    return worker_id


def authenticate(ctx: NodeContext, message: dict, conn: Connection) -> dict:
    """(reference fl_events.py:131-166) JWT verification → worker id."""
    data = message.get(MSG_FIELD.DATA) or {}
    response: dict[str, Any] = {}
    try:
        name = data.get("model_name")
        version = data.get("model_version")
        filters = {"name": name}
        if version:
            filters["version"] = version
        process = ctx.fl.process_manager.first(**filters)
        server_config = ctx.fl.process_manager.get_configs(
            fl_process_id=process.id, is_server_config=True
        )
        verify_token(data.get("auth_token"), server_config)
        worker_id = assign_worker_id(ctx, conn, _handler_of(ctx))
        response[CYCLE.STATUS] = SUCCESS
        response[MSG_FIELD.WORKER_ID] = worker_id
        response[MSG_FIELD.REQUIRES_SPEED_TEST] = requires_speed_test(
            ctx, name, version
        )
    except Exception as err:  # noqa: BLE001 — protocol boundary
        response[ERROR] = str(err)
    return {
        MSG_FIELD.TYPE: MODEL_CENTRIC_FL_EVENTS.AUTHENTICATE,
        MSG_FIELD.DATA: response,
    }


def cycle_request(ctx: NodeContext, message: dict, conn: Connection) -> dict:
    """(reference fl_events.py:169-234) speed-field validation → assign."""
    data = message.get(MSG_FIELD.DATA) or {}
    response: dict[str, Any] = {}
    try:
        worker_id = data.get(MSG_FIELD.WORKER_ID)
        name = data.get(MSG_FIELD.MODEL)
        version = data.get(CYCLE.VERSION)
        worker = ctx.fl.worker_manager.get(id=worker_id)
        fields_map = {
            CYCLE.PING: "ping",
            CYCLE.DOWNLOAD: "avg_download",
            CYCLE.UPLOAD: "avg_upload",
        }
        speed_required = requires_speed_test(ctx, name, version)
        for request_field, db_field in fields_map.items():
            if request_field in data:
                value = data.get(request_field)
                if not isinstance(value, (float, int)) or isinstance(
                    value, bool
                ) or value < 0:
                    raise E.PyGridError(
                        f"'{request_field}' needs to be a positive number"
                    )
                setattr(worker, db_field, float(value))
            elif speed_required:
                raise E.PyGridError(f"'{request_field}' is required")
        ctx.fl.worker_manager.update(worker)
        response = ctx.fl.assign(name, version, worker)
    except E.CycleNotFoundError:
        response[CYCLE.STATUS] = CYCLE.REJECTED
    except E.MaxCycleLimitExceededError as err:
        response[CYCLE.STATUS] = CYCLE.REJECTED
        response[MSG_FIELD.MODEL] = getattr(err, "name", None)
    except Exception as err:  # noqa: BLE001 — protocol boundary
        response[CYCLE.STATUS] = CYCLE.REJECTED
        response[ERROR] = str(err)
    return {
        MSG_FIELD.TYPE: MODEL_CENTRIC_FL_EVENTS.CYCLE_REQUEST,
        MSG_FIELD.DATA: response,
    }


def get_model(ctx: NodeContext, message: dict, conn: Connection) -> dict:
    """WS twin of GET /model-centric/get-model: request-key-gated download
    of the current checkpoint, served from the ModelManager's per-
    checkpoint wire-blob cache (serialized once per round, not once per
    worker). Over binary framing the blob travels as raw bytes; over JSON
    it goes out base64 (JSON cannot carry bytes)."""
    data = message.get(MSG_FIELD.DATA) or {}
    response: dict[str, Any] = {}
    try:
        model_id = int(data.get(MSG_FIELD.MODEL_ID))
        model = ctx.fl.model_manager.get(id=model_id)
        cycle = ctx.fl.cycle_manager.last(model.fl_process_id)
        worker = ctx.fl.worker_manager.get(id=data.get(MSG_FIELD.WORKER_ID))
        ctx.fl.cycle_manager.validate(
            worker.id, cycle.id, data.get(CYCLE.KEY)
        )
        if conn.binary_frame and conn.wire_codec:
            # serve the checkpoint as a pre-compressed v2 frame straight
            # from the per-checkpoint blob cache — compressed once per
            # round, not once per worker — and tell the WS endpoint not
            # to re-compress the envelope around it
            blob = ctx.fl.model_manager.load_encoded(
                model_id,
                precision=data.get("precision"),
                codec=conn.wire_codec,
            )
            response["model_wire"] = "v2-frame"
            conn.suppress_frame_codec = True
        else:
            blob = ctx.fl.model_manager.load_encoded(
                model_id, precision=data.get("precision")
            )
        codec = conn.codec_label()
        telemetry.timeline.add_bytes(cycle.id, "download", codec, len(blob))
        telemetry.incr("model_download_bytes_total", len(blob), codec=codec)
        response[CYCLE.STATUS] = SUCCESS
        response[MSG_FIELD.MODEL] = (
            blob if conn.binary_frame else base64.b64encode(blob).decode()
        )
    except Exception as err:  # noqa: BLE001 — protocol boundary
        response[ERROR] = str(err)
    return {
        MSG_FIELD.TYPE: MODEL_CENTRIC_FL_EVENTS.GET_MODEL,
        MSG_FIELD.DATA: response,
    }


def report(ctx: NodeContext, message: dict, conn: Connection) -> dict:
    """(reference fl_events.py:237-271) base64 diff → submit."""
    data = message.get(MSG_FIELD.DATA) or {}
    response: dict[str, Any] = {}
    try:
        raw = data.get(CYCLE.DIFF) or b""
        # JSON framing carries the diff base64'd (reference wire contract,
        # fl_events.py:237-271); binary msgpack framing carries raw bytes —
        # no +33% inflation, no megabyte JSON parse. b64decode takes the
        # str directly (no explicit .encode() copy of the megabyte field);
        # raw bytes pass through uncopied.
        if isinstance(raw, str):
            from pygrid_tpu.native import b64_decode_view

            diff = b64_decode_view(raw)  # one C pass, no final copy
        else:
            diff = raw if isinstance(raw, bytes) else bytes(raw)
        ctx.fl.submit_diff(
            data.get(MSG_FIELD.WORKER_ID), data.get(CYCLE.KEY), diff,
            wire_codec=conn.codec_label(),
        )
        response[CYCLE.STATUS] = SUCCESS
    except Exception as err:  # noqa: BLE001 — protocol boundary
        response[ERROR] = str(err)
    return {
        MSG_FIELD.TYPE: MODEL_CENTRIC_FL_EVENTS.REPORT,
        MSG_FIELD.DATA: response,
    }


def report_partial(ctx: NodeContext, message: dict, conn: Connection) -> dict:
    """A sub-aggregator's subtree report (docs/AGGREGATION.md): one
    count-weighted partial diff sum plus the (worker_id, request_key)
    pairs it folded — the node validates every pair exactly like a
    direct report, then merges the sum into the cycle accumulator
    straight from the zero-copy wire view."""
    data = message.get(MSG_FIELD.DATA) or {}
    response: dict[str, Any] = {}
    try:
        raw = data.get(CYCLE.DIFF) or b""
        if isinstance(raw, str):
            from pygrid_tpu.native import b64_decode_view

            diff = b64_decode_view(raw)
        else:
            diff = raw if isinstance(raw, bytes) else bytes(raw)
        workers = data.get("workers")
        if not isinstance(workers, (list, tuple)):
            raise E.PyGridError(
                "partial report needs a 'workers' list of "
                "[worker_id, request_key] pairs"
            )
        entries = []
        for pair in workers:
            if not isinstance(pair, (list, tuple)) or len(pair) != 2:
                raise E.PyGridError(
                    "each 'workers' entry must be a "
                    "[worker_id, request_key] pair"
                )
            entries.append((str(pair[0]), str(pair[1])))
        count = data.get("count", len(entries))
        weight_sum = data.get("weight_sum")
        if weight_sum is not None and (
            isinstance(weight_sum, bool)
            or not isinstance(weight_sum, (int, float))
        ):
            raise E.PyGridError("weight_sum must be a JSON number")
        ctx.fl.submit_partial(
            entries,
            diff,
            count,
            weight_sum=weight_sum,
            masked=bool(data.get("masked")),
            wire_codec=conn.codec_label(),
        )
        response[CYCLE.STATUS] = SUCCESS
    except Exception as err:  # noqa: BLE001 — protocol boundary
        response[ERROR] = str(err)
    return {
        MSG_FIELD.TYPE: MODEL_CENTRIC_FL_EVENTS.REPORT_PARTIAL,
        MSG_FIELD.DATA: response,
    }


def report_metrics(ctx: NodeContext, message: dict, conn: Connection) -> dict:
    """Client-reported training metrics for an assignment (this
    framework's extension — the reference has no structured metrics,
    SURVEY §5.5). Sample-weighted per-cycle aggregation is served by
    GET /model-centric/cycle-metrics."""
    data = message.get(MSG_FIELD.DATA) or {}
    response: dict[str, Any] = {}
    try:
        ctx.fl.cycle_manager.submit_worker_metrics(
            data.get(MSG_FIELD.WORKER_ID),
            data.get(CYCLE.KEY),
            data.get("metrics") or {},
        )
        response[CYCLE.STATUS] = SUCCESS
    except Exception as err:  # noqa: BLE001 — protocol boundary
        response[ERROR] = str(err)
    return {
        MSG_FIELD.TYPE: MODEL_CENTRIC_FL_EVENTS.REPORT_METRICS,
        MSG_FIELD.DATA: response,
    }


# ── secure-aggregation rounds (this framework's extension; secagg_service) ───


def _secagg_event(msg_type: str, fn) -> Callable:
    """Wrap a SecAggService call in the standard {type, data} envelope with
    protocol-boundary error capture (the shape every FL event returns)."""

    def handler(ctx: NodeContext, message: dict, conn: Connection) -> dict:
        data = message.get(MSG_FIELD.DATA) or {}
        response: dict[str, Any] = {}
        try:
            response = fn(ctx.fl.cycle_manager.secagg, data)
        except Exception as err:  # noqa: BLE001 — protocol boundary
            response = {ERROR: str(err)}
        return {MSG_FIELD.TYPE: msg_type, MSG_FIELD.DATA: response}

    return handler


secagg_advertise = _secagg_event(
    MODEL_CENTRIC_FL_EVENTS.SECAGG_ADVERTISE,
    lambda svc, d: svc.advertise(
        d.get(MSG_FIELD.WORKER_ID), d.get(CYCLE.KEY), d.get("public_key")
    ),
)
secagg_roster = _secagg_event(
    MODEL_CENTRIC_FL_EVENTS.SECAGG_ROSTER,
    lambda svc, d: svc.roster(d.get(MSG_FIELD.WORKER_ID), d.get(CYCLE.KEY)),
)
secagg_shares = _secagg_event(
    MODEL_CENTRIC_FL_EVENTS.SECAGG_SHARES,
    lambda svc, d: svc.submit_shares(
        d.get(MSG_FIELD.WORKER_ID), d.get(CYCLE.KEY), d.get("shares") or {}
    ),
)
secagg_status = _secagg_event(
    MODEL_CENTRIC_FL_EVENTS.SECAGG_STATUS,
    lambda svc, d: svc.status(d.get(MSG_FIELD.WORKER_ID), d.get(CYCLE.KEY)),
)
secagg_unmask = _secagg_event(
    MODEL_CENTRIC_FL_EVENTS.SECAGG_UNMASK,
    lambda svc, d: svc.submit_unmask_shares(
        d.get(MSG_FIELD.WORKER_ID),
        d.get(CYCLE.KEY),
        d.get("b_shares") or {},
        d.get("sk_shares") or {},
    ),
)


# ── data-centric control events (reference control_events.py) ────────────────


def get_node_infos(ctx: NodeContext, message: dict, conn: Connection) -> dict:
    return {
        MSG_FIELD.NODE_ID: ctx.local_worker.id,
        MSG_FIELD.SYFT_VERSION: __version__,
    }


def authentication(ctx: NodeContext, message: dict, conn: Connection) -> dict:
    """(reference control_events.py:28-42) credentials → per-user session."""
    try:
        session, token = ctx.sessions.login(
            message.get(MSG_FIELD.USERNAME_FIELD),
            message.get(MSG_FIELD.PASSWORD_FIELD),
        )
    except E.PyGridError:
        return {ERROR: "Invalid username/password!"}
    conn.session = session
    # federate the user's worker with the node's singleton so pointers to
    # either store resolve over this connection
    ctx.local_worker.add_worker(session.worker)
    # grid peers dialed before this login become reachable from this session
    for peer_id, peer in ctx.local_worker._known_workers.items():
        session.worker._known_workers.setdefault(peer_id, peer)
    # session workers answer crypto-deal requests with the node's dealer
    session.worker.crypto_provider = ctx.crypto_provider
    return {SUCCESS: "True", MSG_FIELD.NODE_ID: session.worker.id, "token": token}


def connect_grid_nodes(ctx: NodeContext, message: dict, conn: Connection) -> dict:
    """(reference control_events.py:44-54) node-to-node mesh: dial the peer
    and register it as a known worker."""
    peer_id = message.get("id")
    if peer_id not in ctx.local_worker._known_workers:
        from pygrid_tpu.client.data_centric import DataCentricFLClient

        peer = DataCentricFLClient(message.get("address"), id=peer_id)
        ctx.local_worker._known_workers[peer_id] = peer
        # session workers route through the same peer (tensors live there)
        for session in ctx.sessions.all_sessions():
            if session._worker is not None:
                session._worker._known_workers.setdefault(peer_id, peer)
    return {"status": "Succesfully connected."}


def socket_ping(ctx: NodeContext, message: dict, conn: Connection) -> dict:
    return {MSG_FIELD.ALIVE: "True"}


# ── data-centric syft events (reference syft_events.py) ──────────────────────


def forward_binary_message(
    ctx: NodeContext,
    message: bytes | bytearray,
    conn: Connection,
    decoded: Any = None,
) -> bytes:
    """(reference syft_events.py:18-45) binary wire msg → per-user worker.
    ``decoded`` carries the already-deserialized message when the WS
    dispatcher peeked at the frame (one decode per frame, not two)."""
    if conn.session is None:
        return serialize(
            {"error_type": "AuthorizationError", "message": "login required"}
        )
    worker = conn.worker
    if len(worker.store) == 0:
        recover_objects(worker, ctx.kv)
    if decoded is not None:
        return worker.recv_decoded_msg(decoded, user=conn.session.username)
    return worker._recv_msg(bytes(message), user=conn.session.username)


def syft_command(ctx: NodeContext, message: dict, conn: Connection) -> dict:
    """JSON variant of the binary path (reference syft_events.py:49-59)."""
    msg = deserialize(binascii.unhexlify(message[MSG_FIELD.DATA]))
    response = conn.worker.recv_obj_msg(msg, user=conn.session.username)
    return {MSG_FIELD.DATA: binascii.hexlify(serialize(response)).decode()}


# ── data-centric model events (reference model_events.py) ────────────────────


def _authenticated(conn: Connection) -> None:
    if conn.session is None:
        raise E.AuthorizationError("authentication required")


def host_model(ctx: NodeContext, message: dict, conn: Connection) -> dict:
    _authenticated(conn)
    try:
        # missing fields bounce typed, not as a cryptic KeyError string
        # from the dispatch boundary (gridlint GL4 satellite audit)
        for field_name in (MSG_FIELD.MODEL, MSG_FIELD.MODEL_ID):
            if field_name not in message:
                raise E.MissingRequestKeyError(
                    f"missing required field '{field_name}'"
                )
        serialized = message[MSG_FIELD.MODEL]
        if isinstance(serialized, str):
            # native single-pass decode straight into the stored buffer —
            # the old base64.b64decode → bytes(...) round trip copied the
            # megabyte model twice
            from pygrid_tpu.native import b64_decode

            try:
                serialized = b64_decode(serialized)
            except ValueError:
                # line-wrapped / whitespace-laced base64 (MIME tooling,
                # encodebytes) decoded under the old permissive path and
                # must keep working — the strict kernel is the fast path,
                # not a contract change
                try:
                    serialized = base64.b64decode(serialized)
                except (binascii.Error, ValueError) as err:
                    # formerly escaped as an untyped binascii.Error
                    raise E.PyGridError(
                        f"model field is not valid base64: {err}"
                    ) from err
        elif not isinstance(serialized, bytes):
            serialized = bytes(serialized)
        return ctx.models.save(
            ctx.local_worker.id,
            serialized,
            message[MSG_FIELD.MODEL_ID],
            allow_download=str(message.get(MSG_FIELD.ALLOW_DOWNLOAD)) == "True",
            allow_remote_inference=str(
                message.get(MSG_FIELD.ALLOW_REMOTE_INFERENCE)
            )
            == "True",
            mpc=str(message.get(MSG_FIELD.MPC)) == "True",
        )
    except E.PyGridError as err:
        return {SUCCESS: False, ERROR: str(err)}


#: KV-cache allocation cap for run-generation (elements, k+v combined):
#: 2^28 ≈ 268M elements = 1 GB at f32 — generous for serving, far below
#: what would OOM the node's chip/host from one hostile frame
_MAX_GENERATION_CACHE_ELEMENTS = 1 << 28

#: memoized jitted decode programs, keyed on everything trace-relevant
#: ((cfg ints, n_new, seeded) — temperature is a TRACED argument in the
#: sampled program, so one compile serves every temperature;
#: params/prompt shapes key jit's own cache); bounded so hostile n_new
#: variety can't grow it without limit
_GENERATION_JIT: dict = {}


def _generation_fn(cfg, n_new: int, seeded: bool):
    cache_key = (tuple(cfg), n_new, seeded)
    fn = _GENERATION_JIT.pop(cache_key, None)
    if fn is not None:
        # LRU touch: re-insert at the back so hot programs survive a
        # client cycling n_new values (dicts iterate insertion-ordered)
        _GENERATION_JIT[cache_key] = fn
    if fn is None:
        import jax

        from pygrid_tpu.models import decode

        if len(_GENERATION_JIT) >= 64:
            # evict only the single least-recently-used entry — clearing
            # the whole dict let one hostile client flush every hot
            # compiled program for all models at once
            _GENERATION_JIT.pop(next(iter(_GENERATION_JIT)))
        if seeded:
            fn = jax.jit(
                lambda p, x, k, temp: decode.generate(
                    p, x, n_new, cfg, temperature=temp, key=k
                )
            )
        else:
            fn = jax.jit(
                lambda p, x: decode.generate(p, x, n_new, cfg)
            )
        _GENERATION_JIT[cache_key] = fn
    return fn


def _prepare_generation(ctx: NodeContext, message: dict):
    """Validate a run-generation message end to end. Returns either an
    error-response dict or ``(hosted, prompt, n_new, temperature,
    seed)`` with the hosted bundle parsed into
    ``hosted.generation_cache``. Shared by the WS handler and the async
    HTTP route so the two doors cannot drift on the typed-error
    contract."""
    import math

    import numpy as np

    got = _servable_and_data(ctx, message)
    if isinstance(got, dict):
        return got
    hosted, prompt = got
    from pygrid_tpu.models import decode

    # parse + device-upload the bundle ONCE per hosted model (the
    # HostedModel lives in the process-wide ModelCache, so every
    # later request reuses the on-device params)
    if hosted.generation_cache is None:
        hosted.generation_cache = decode.from_bundle(hosted.model)
    cfg, _params = hosted.generation_cache
    prompt = np.asarray(prompt)
    if (
        prompt.ndim != 2
        or prompt.shape[0] < 1
        or prompt.shape[1] < 1
        or not np.issubdtype(prompt.dtype, np.integer)
    ):
        return {
            SUCCESS: False,
            ERROR: "prompt must be non-empty int tokens [B, P]",
        }
    # bound what the untrusted B actually sizes — per-request KV work is
    # 2 × [layers, B, max_len, H, dh] (B is the only request-controlled
    # factor; the rest is the hosted config), so the cap is on total
    # cache elements, mirroring the MAX_OPLIST_ELEMENTS posture in
    # plans/translators.py. The batch engine's cache is allocated per
    # SLOT, not per request, but the same cap bounds how many rows one
    # frame may enqueue.
    cache_elems = (
        2 * cfg.n_layers * prompt.shape[0] * cfg.max_len * cfg.d_model
    )
    if cache_elems > _MAX_GENERATION_CACHE_ELEMENTS:
        return {
            SUCCESS: False,
            ERROR: (
                f"prompt batch of {prompt.shape[0]} would need a "
                f"{cache_elems:,}-element KV cache (cap "
                f"{_MAX_GENERATION_CACHE_ELEMENTS:,})"
            ),
        }
    if prompt.min() < 0 or prompt.max() >= cfg.vocab:
        return {
            SUCCESS: False,
            ERROR: f"prompt token out of range [0, {cfg.vocab})",
        }
    raw_n_new = message.get("n_new", 16)
    # same wire contract as temperature below: a JSON integer — bools,
    # strings ("8" would int()-coerce) and fractional floats all bounce
    if (
        isinstance(raw_n_new, bool)
        or not isinstance(raw_n_new, (int, float))
        or (isinstance(raw_n_new, float) and not math.isfinite(raw_n_new))
        or int(raw_n_new) != raw_n_new
    ):
        return {SUCCESS: False, ERROR: "n_new must be a JSON integer"}
    n_new = int(raw_n_new)
    if n_new < 1:
        return {SUCCESS: False, ERROR: "n_new must be >= 1"}
    raw_temp = message.get("temperature", 0.0)
    if isinstance(raw_temp, bool) or not isinstance(
        raw_temp, (int, float)
    ):
        # float() would coerce JSON true to 1.0 (silently sampling) and
        # numeric strings to their value — the wire contract is a JSON
        # number, everything else bounces typed
        return {
            SUCCESS: False,
            ERROR: "temperature must be a JSON number (bool/string rejected)",
        }
    temperature = float(raw_temp)
    # `== 0 or > 0` rejects both negatives AND NaN (NaN fails both);
    # isfinite rejects Infinity, which would otherwise collapse the
    # logits to zero and silently serve uniform-random tokens
    if not math.isfinite(temperature) or not (
        temperature == 0.0 or temperature > 0.0
    ):
        return {SUCCESS: False, ERROR: "temperature must be finite and >= 0"}
    seed = message.get("seed")
    if seed is not None:
        if (
            isinstance(seed, bool)
            or not isinstance(seed, (int, float))
            or (isinstance(seed, float) and not math.isfinite(seed))
            or int(seed) != seed
        ):
            return {SUCCESS: False, ERROR: "seed must be a JSON integer"}
        seed = int(seed)
        # PRNGKey overflows int64 with an uncaught OverflowError —
        # bound the client-supplied value to the typed-error contract
        if not 0 <= seed < 2**63:
            return {
                SUCCESS: False,
                ERROR: "seed must be in [0, 2**63)",
            }
    return hosted, prompt, n_new, temperature, seed


def _legacy_generate(hosted, prompt, n_new: int, temperature, seed):
    """The pre-engine per-request path (one whole-generation XLA program
    jitted per distinct ``n_new``) — kept as the ``PYGRID_SERVING=off``
    escape hatch and as the baseline ``bench_serving`` measures the
    batch engine against."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    cfg, params = hosted.generation_cache
    if temperature > 0.0 and seed is None:
        # unseeded sampling must actually vary across requests
        seed = int.from_bytes(os.urandom(4), "big")
    sampled = temperature > 0.0
    fn = _generation_fn(cfg, n_new, sampled)
    if sampled:
        toks = fn(
            params,
            jnp.asarray(prompt),
            jax.random.PRNGKey(int(seed)),
            jnp.float32(temperature),
        )
    else:
        toks = fn(params, jnp.asarray(prompt))
    return np.asarray(toks)


def run_generation(ctx: NodeContext, message: dict, conn: Connection) -> dict:
    """Autoregressive generation from a hosted transformer bundle —
    the serving twin of ``run_inference`` for the generative model
    family. Message fields: ``model_id``, ``data`` (serialized int
    prompt [B, P]), ``n_new``, optional ``temperature`` + ``seed``.
    Gated by the same ``allow_remote_inference`` flag.

    Since the serving engine (``pygrid_tpu/serving/``, docs/SERVING.md)
    this handler is a thin enqueue-and-await wrapper: the request joins
    the model's continuous batch and this (executor) thread blocks on
    the result future while the engine's dedicated thread drives the
    device — concurrent requests share one persistent batched program
    instead of serializing whole-generation XLA calls, and a full queue
    answers a typed busy error instead of piling up. Greedy results are
    bit-identical to the direct ``decode.generate`` path;
    ``PYGRID_SERVING=off`` restores the legacy per-request programs."""
    _authenticated(conn)
    import numpy as np

    try:
        prep = _prepare_generation(ctx, message)
        if isinstance(prep, dict):
            return prep
        hosted, prompt, n_new, temperature, seed = prep
        if os.environ.get("PYGRID_SERVING", "").lower() in ("off", "0"):
            toks = _legacy_generate(hosted, prompt, n_new, temperature, seed)
        else:
            engine = ctx.serving.engine_for(
                str(message[MSG_FIELD.MODEL_ID]), hosted
            )
            toks = engine.submit(prompt, n_new, temperature, seed)
        return {SUCCESS: True, "tokens": np.asarray(toks).tolist()}
    except E.ServerBusyError as err:
        return {SUCCESS: False, "busy": True, ERROR: str(err)}
    except (E.PyGridError, ValueError, TypeError) as err:
        return {SUCCESS: False, ERROR: str(err)}


def delete_model(ctx: NodeContext, message: dict, conn: Connection) -> dict:
    _authenticated(conn)
    try:
        result = ctx.models.delete(
            ctx.local_worker.id, message[MSG_FIELD.MODEL_ID]
        )
        # the serving engine holds the bundle's device params + slot
        # cache — deleting the model must release them
        ctx.serving.evict(str(message[MSG_FIELD.MODEL_ID]))
        return result
    except E.PyGridError as err:
        return {SUCCESS: False, ERROR: str(err)}


def get_models(ctx: NodeContext, message: dict, conn: Connection) -> dict:
    _authenticated(conn)
    return {MSG_FIELD.MODELS: ctx.models.models(ctx.local_worker.id)}


#: shared by run_inference / run_generation: both routes gate on the
#: same allow_remote_inference flag and accept the same base64-or-bytes
#: serialized data field
_NOT_ALLOWED = {
    SUCCESS: False,
    "not_allowed": True,
    ERROR: "You're not allowed to run inferences on this model.",
}


def _servable_and_data(ctx: NodeContext, message: dict):
    """(hosted_model, deserialized_data) for an inference-family route,
    or an error-response dict when the permission gate rejects. Missing
    fields raise typed PyGridErrors so the caller's error contract
    (every defect -> {success: False, error: ...}) holds."""
    for field_name in (MSG_FIELD.MODEL_ID, MSG_FIELD.DATA):
        if field_name not in message:
            raise E.PyGridError(f"missing required field '{field_name}'")
    if len(ctx.local_worker.store) == 0:
        recover_objects(ctx.local_worker, ctx.kv)
    hosted = ctx.models.get(ctx.local_worker.id, message[MSG_FIELD.MODEL_ID])
    if not hosted.allow_remote_inference:
        return dict(_NOT_ALLOWED)
    blob = message[MSG_FIELD.DATA]
    if isinstance(blob, str):
        try:
            blob = base64.b64decode(blob)
        except (binascii.Error, ValueError) as err:
            # formerly escaped as an untyped binascii.Error string
            raise E.PyGridError(
                f"data field is not valid base64: {err}"
            ) from err
    try:
        payload = deserialize(bytes(blob))
    except Exception as err:  # noqa: BLE001 — msgpack raises its own zoo
        raise E.PyGridError(
            f"data field is not a valid serialized payload: {err}"
        ) from err
    return hosted, payload


def run_inference(ctx: NodeContext, message: dict, conn: Connection) -> dict:
    """(reference model_events.py:77-129) run a hosted model on submitted
    data; predictions return as a plain list."""
    _authenticated(conn)
    import numpy as np

    try:
        got = _servable_and_data(ctx, message)
        if isinstance(got, dict):
            return got
        hosted, data = got
        output = hosted.model(data)
        if isinstance(output, (tuple, list)):
            output = output[0]
        return {SUCCESS: True, "prediction": np.asarray(output).tolist()}
    except E.PyGridError as err:
        return {SUCCESS: False, ERROR: str(err)}


# ── user / role / group WS twins (reference {user,role,group}_related.py) ────
# handlers live in pygrid_tpu.users.events so the Network app serves the
# identical RBAC surface (the reference duplicates them per app)

_USER_HANDLERS = USER_HANDLERS

# ── dispatch ─────────────────────────────────────────────────────────────────

ROUTES: dict[str, Callable[[NodeContext, dict, Connection], dict]] = {
    CONTROL_EVENTS.SOCKET_PING: socket_ping,
    MODEL_CENTRIC_FL_EVENTS.HOST_FL_TRAINING: host_federated_training,
    MODEL_CENTRIC_FL_EVENTS.AUTHENTICATE: authenticate,
    MODEL_CENTRIC_FL_EVENTS.CYCLE_REQUEST: cycle_request,
    MODEL_CENTRIC_FL_EVENTS.GET_MODEL: get_model,
    MODEL_CENTRIC_FL_EVENTS.REPORT: report,
    MODEL_CENTRIC_FL_EVENTS.REPORT_PARTIAL: report_partial,
    MODEL_CENTRIC_FL_EVENTS.REPORT_METRICS: report_metrics,
    MODEL_CENTRIC_FL_EVENTS.SECAGG_ADVERTISE: secagg_advertise,
    MODEL_CENTRIC_FL_EVENTS.SECAGG_ROSTER: secagg_roster,
    MODEL_CENTRIC_FL_EVENTS.SECAGG_SHARES: secagg_shares,
    MODEL_CENTRIC_FL_EVENTS.SECAGG_STATUS: secagg_status,
    MODEL_CENTRIC_FL_EVENTS.SECAGG_UNMASK: secagg_unmask,
    REQUEST_MSG.GET_ID: get_node_infos,
    REQUEST_MSG.CONNECT_NODE: connect_grid_nodes,
    REQUEST_MSG.HOST_MODEL: host_model,
    REQUEST_MSG.RUN_INFERENCE: run_inference,
    REQUEST_MSG.RUN_GENERATION: run_generation,
    REQUEST_MSG.DELETE_MODEL: delete_model,
    REQUEST_MSG.LIST_MODELS: get_models,
    REQUEST_MSG.AUTHENTICATE: authentication,
    "syft-command": syft_command,
    **_USER_HANDLERS,
}

_socket_handlers: dict[int, SocketHandler] = {}


def _handler_of(ctx: NodeContext) -> SocketHandler:
    return _socket_handlers.setdefault(id(ctx), SocketHandler())


def _record_handler_failure(ctx: NodeContext, event: str, err: Exception):
    """An exception that LEAKED past a handler (the typed validation
    paths return error dicts and never reach here) is a defect worth a
    postmortem: note it on the flight-recorder ring and trigger a
    rate-limited crash dump on a side thread — the dispatch path pays
    one dict append, never file I/O. Best-effort by contract: the
    boundary's promise is the typed error dict, and a recorder failure
    (thread exhaustion during the very storm this exists for) must not
    replace the exception being reported."""
    if not telemetry.recorder.enabled():
        return
    try:
        telemetry.recorder.note(
            "handler.exception",
            event=event,
            error=str(err),
            error_type=type(err).__name__,
        )
        # rate-limit check FIRST: during a storm, everything past this
        # line (engine-lock snapshot, redaction, a writer thread) runs
        # at most once per interval, not once per exception
        if telemetry.recorder.should_dump("handler_exception"):
            telemetry.recorder.dump_soon(
                "handler_exception",
                snapshot={"event": event, "serving": ctx.serving.stats()},
                error=err,
            )
    except Exception:  # noqa: BLE001 — telemetry must not mask the error
        logger.exception("flight-recorder capture failed")


def _incoming_trace(conn: Connection, parsed: Any):
    """The message's trace context: the wire-v2 frame header (one-shot,
    set by the WS endpoint) wins; legacy framing carries a ``trace``
    field on the envelope; absence means the server synthesizes a root
    (``trace.serve``) so a legacy client's cycle is still traced."""
    incoming, conn.incoming_trace = conn.incoming_trace, None
    if incoming is None and isinstance(parsed, dict):
        incoming = trace.parse_header(parsed.get("trace"))
    return incoming


def _traced_call(conn: Connection, parsed: Any, event: str, fn):
    """Dispatch one event under a served span: adopts (or synthesizes)
    the trace, records the handler span + latency histogram, and leaves
    the span on ``conn.last_trace`` for the response frame's header."""
    import time

    incoming = _incoming_trace(conn, parsed)
    t0 = time.perf_counter()
    with trace.serve(incoming) as tctx:
        conn.last_trace = tctx
        result = fn()
    dt = time.perf_counter() - t0
    telemetry.observe("node_event_seconds", dt, event=event)
    telemetry.record(
        "node.event",
        name=event,
        trace_id=tctx.trace_id,
        span_id=tctx.span_id,
        parent_id=incoming.span_id if incoming is not None else None,
        duration_s=dt,
    )
    return result


def route_requests(
    ctx: NodeContext, message: str | bytes | bytearray, conn: Connection
):
    """(reference events/__init__.py:61-87) one message in, one response out.
    Binary frames carrying a ``{type: ...}`` dict are the msgpack twins of
    the JSON events (the fast wire for FL reports: raw diff bytes, no
    base64, no megabyte JSON parse); any other binary frame routes to the
    per-user worker as before. JSON dispatches on `type`; request_id echoes
    back in either framing."""
    import json

    if isinstance(message, (bytes, bytearray, memoryview)):
        conn.binary_frame = True
        try:
            try:
                parsed = deserialize(message)
            except Exception:  # noqa: BLE001 — let the worker frame the error
                return _traced_call(
                    conn, None, "syft-binary",
                    lambda: forward_binary_message(ctx, message, conn),
                )
            if isinstance(parsed, dict) and parsed.get(MSG_FIELD.TYPE) in ROUTES:
                request_id = parsed.get(MSG_FIELD.REQUEST_ID)
                event = parsed[MSG_FIELD.TYPE]

                def _dispatch():
                    try:
                        return ROUTES[event](ctx, parsed, conn)
                    except Exception as err:  # noqa: BLE001 — protocol boundary
                        _record_handler_failure(ctx, event, err)
                        return {ERROR: str(err)}

                response = _traced_call(conn, parsed, event, _dispatch)
                if request_id:
                    response[MSG_FIELD.REQUEST_ID] = request_id
                return serialize(response)
            return _traced_call(
                conn, parsed, "syft-binary",
                lambda: forward_binary_message(
                    ctx, message, conn, decoded=parsed
                ),
            )
        finally:
            conn.binary_frame = False

    request_id = None
    try:
        parsed = json.loads(message)
        request_id = parsed.get(MSG_FIELD.REQUEST_ID)
        event = parsed[MSG_FIELD.TYPE]
        handler = ROUTES[event]

        def _dispatch_json():
            try:
                return handler(ctx, parsed, conn)
            except Exception as err:  # noqa: BLE001 — protocol boundary
                _record_handler_failure(ctx, event, err)
                return {ERROR: str(err)}

        response = _traced_call(conn, parsed, event, _dispatch_json)
    except Exception as err:  # noqa: BLE001 — protocol boundary
        response = {ERROR: str(err)}
    if request_id:
        response[MSG_FIELD.REQUEST_ID] = request_id
    return json.dumps(response, default=_json_bytes)


def _json_bytes(obj: Any) -> str:
    """JSON framing of handler responses that carry payload bytes (the
    handlers base64 for JSON themselves via ``conn.binary_frame``; this
    default is the safety net so a bytes leak degrades to base64 text
    instead of a 500)."""
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return base64.b64encode(bytes(obj)).decode()
    # json.dumps' default-hook contract REQUIRES TypeError (anything
    # else aborts serialization differently); json internals call this,
    # not the route dispatch, so GL604's boundary reachability holds
    raise TypeError(f"not JSON serializable: {type(obj)!r}")
