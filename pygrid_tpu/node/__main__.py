"""Node CLI entrypoint.

Parity surface: reference ``apps/node/src/__main__.py:17-102`` — argparse
flags (--id/--port/--host/--network/--num_replicas/--start_local_db), a POST
of ``{node-id, node-address}`` to the Network's ``/join`` at boot (:78-83),
then serve. Env fallbacks mirror the reference: NODE_ID, GRID_NETWORK_URL,
PORT, DATABASE_URL.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os

logger = logging.getLogger("pygrid_tpu.node")


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(description="pygrid-tpu Node")
    parser.add_argument(
        "--id", default=os.environ.get("NODE_ID", "node"), help="node id"
    )
    parser.add_argument(
        "--port", type=int, default=int(os.environ.get("PORT", 5000))
    )
    parser.add_argument("--host", default=os.environ.get("HOST", "0.0.0.0"))
    parser.add_argument(
        "--network",
        default=os.environ.get("GRID_NETWORK_URL"),
        help="grid Network URL to join",
    )
    parser.add_argument(
        "--num_replicas",
        type=int,
        default=int(os.environ.get("N_REPLICA", 0)) or None,
    )
    parser.add_argument(
        "--start_local_db",
        action="store_true",
        help="use a local sqlite file instead of in-memory",
    )
    return parser.parse_args(argv)


async def join_network(network_url: str, node_id: str, address: str) -> None:
    """POST {node-id, node-address} to the Network (reference :78-83)."""
    import aiohttp

    try:
        async with aiohttp.ClientSession() as session:
            async with session.post(
                network_url.rstrip("/") + "/join",
                json={"node-id": node_id, "node-address": address},
                timeout=aiohttp.ClientTimeout(total=10),
            ) as resp:
                logger.info("joined network %s: %s", network_url, resp.status)
    except Exception as err:  # noqa: BLE001 — boot resilience
        logger.warning("could not join network %s: %s", network_url, err)


def main(argv=None) -> None:
    from aiohttp import web

    from pygrid_tpu.node import create_app

    args = parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    database_url = (
        f"node_{args.id}.db" if args.start_local_db
        else os.environ.get("DATABASE_URL", ":memory:")
    )
    address = os.environ.get(
        "NODE_ADDRESS", f"http://localhost:{args.port}"
    )
    app = create_app(
        args.id,
        database_url=database_url,
        network_url=args.network,
        num_replicas=args.num_replicas,
    )
    app["node"].address = address
    if args.network:
        async def _on_startup(app_):
            asyncio.get_running_loop().create_task(
                join_network(args.network, args.id, address)
            )

        app.on_startup.append(_on_startup)
    web.run_app(app, host=args.host, port=args.port)


if __name__ == "__main__":
    main()
