"""Distributed SMPC: shares living on real grid nodes.

Parity surface: the reference's cross-node sharing flow
(``x.fix_prec().share(alice, bob, charlie, dan)`` sends one share per Node
over the WS binary path — SURVEY.md §3.4; host selection in chunks of 4,
``apps/network/src/app/routes/network.py:16,98-131``).

TPU-first split of responsibilities: heavy SMPC *compute* (Beaver
mul/matmul over batches of parties) runs in the on-chip vmapped plane
(:mod:`pygrid_tpu.smpc.kernels` / the Pallas matmul); this module covers
the *protocol* plane — placing one additive share per real node, running
the share-local linear algebra remotely via pointer ops (additive
homomorphism: add/sub/public-scale never need communication), and
reconstructing by opening every share. Shares travel and rest as int64
(two's complement of the ring element); numpy's wrapping int64 arithmetic
on the remote parties IS ring-2^64 arithmetic.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from pygrid_tpu.smpc import ring as R
from pygrid_tpu.smpc.additive import AdditiveSharingTensor
from pygrid_tpu.smpc.fixed import FixedPointEncoder


class RemoteSharedTensor:
    """Handle to a secret whose additive shares live on remote nodes.

    ``pointers[i]`` points at owner i's int64 share array. Linear ops are
    share-local (one remote op per node, no cross-node traffic); ``get()``
    opens the secret by fetching and summing all shares."""

    def __init__(
        self,
        pointers: list,
        encoder: FixedPointEncoder | None,
    ) -> None:
        self.pointers = list(pointers)
        self.encoder = encoder

    @property
    def n_parties(self) -> int:
        return len(self.pointers)

    @property
    def locations(self) -> list:
        return [p.location for p in self.pointers]

    # --- open ---------------------------------------------------------------

    def get(self, delete: bool = True) -> np.ndarray:
        """Fetch every share, sum in the ring, decode."""
        shares = [
            np.asarray(p.get(delete=delete)).astype(np.int64)
            for p in self.pointers
        ]
        total = R.to_ring(sum_int64_wrapping(shares).astype(np.uint64))
        if self.encoder:
            return self.encoder.decode(total)
        return R.from_ring_signed(total)

    # --- share-local linear algebra (additive homomorphism) ----------------

    def _party_ids(self) -> list:
        return [getattr(p.location, "id", id(p.location)) for p in self.pointers]

    def _zip_op(self, other: "RemoteSharedTensor", op: str):
        if self._party_ids() != other._party_ids():
            raise ValueError(
                "operands are shared over different parties: "
                f"{self._party_ids()} vs {other._party_ids()}"
            )
        mine, theirs = self.encoder, other.encoder
        if (mine is None) != (theirs is None) or (
            mine is not None and mine.scale != theirs.scale
        ):
            raise ValueError("mismatched fixed-point encoders")
        ptrs = [
            getattr(a, op)(b)
            for a, b in zip(self.pointers, other.pointers)
        ]
        return RemoteSharedTensor(ptrs, self.encoder)

    def __add__(self, other: "RemoteSharedTensor") -> "RemoteSharedTensor":
        return self._zip_op(other, "__add__")

    def __sub__(self, other: "RemoteSharedTensor") -> "RemoteSharedTensor":
        return self._zip_op(other, "__sub__")

    def mul_public(self, c: int) -> "RemoteSharedTensor":
        """Multiply by a public integer (share-local; no rescale, so for
        fixed-point secrets ``c`` must be an integer scalar)."""
        if not float(c).is_integer():
            raise ValueError("public factor must be an integer")
        ptrs = [p * np.int64(int(c)) for p in self.pointers]
        return RemoteSharedTensor(ptrs, self.encoder)

    def __repr__(self) -> str:
        locs = [getattr(loc, "id", loc) for loc in self.locations]
        return f"RemoteSharedTensor(parties={locs})"


def sum_int64_wrapping(arrays: Sequence[np.ndarray]) -> np.ndarray:
    """Ring sum of int64 share arrays (numpy wraps on overflow — exactly
    the mod-2^64 semantics the shares need)."""
    with np.errstate(over="ignore"):
        total = arrays[0].copy()
        for a in arrays[1:]:
            total += a
    return total


def share_to_nodes(
    x: np.ndarray,
    clients: Sequence[Any],
    encoder: FixedPointEncoder | None = None,
    tags: Sequence[str] = (),
) -> RemoteSharedTensor:
    """Split ``x`` into len(clients) additive shares, one per node.

    ``clients``: DataCentricFLClient-like locations (anything pointers can
    ``send`` through). Mirrors the reference's
    ``x.fix_prec().share(*nodes)``."""
    owners = [getattr(c, "id", str(i)) for i, c in enumerate(clients)]
    ast = AdditiveSharingTensor.share(
        np.asarray(x), owners, encoder=encoder
    )
    share_arrays = R.from_ring(ast.shares).astype(np.int64)  # [P, ...]
    pointers = []
    for i, client in enumerate(clients):
        pointers.append(client.send(share_arrays[i], tags=set(tags)))
    return RemoteSharedTensor(pointers, encoder)


def fix_prec_share_to_nodes(
    x: np.ndarray,
    clients: Sequence[Any],
    base: int = 10,
    precision_fractional: int = 3,
    tags: Sequence[str] = (),
) -> RemoteSharedTensor:
    """``x.fix_prec().share(alice, bob, …)`` over real nodes."""
    encoder = FixedPointEncoder(base, precision_fractional)
    return share_to_nodes(x, clients, encoder=encoder, tags=tags)
