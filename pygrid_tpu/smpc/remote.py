"""Distributed SMPC: shares living on real grid nodes.

Parity surface: the reference's cross-node sharing flow
(``x.fix_prec().share(alice, bob, charlie, dan)`` sends one share per Node
over the WS binary path — SURVEY.md §3.4; host selection in chunks of 4,
``apps/network/src/app/routes/network.py:16,98-131``) and its flagship
cross-node Beaver matmul with a crypto-provider worker (reference
``tests/data_centric/test_basic_syft_operations.py:383-491``, refill error
path ``events/data_centric/syft_events.py:34-45``).

TPU-first split of responsibilities: heavy SMPC *compute* (Beaver
mul/matmul over batches of parties) runs in the on-chip vmapped plane
(:mod:`pygrid_tpu.smpc.kernels` / the Pallas matmul); this module covers
the *protocol* plane — placing one additive share per real node, running
the share-local algebra remotely via pointer ops, opening only masked
values (Beaver's d/e, the truncation mask m), and reconstructing secrets
by opening every share. Shares travel and rest as int64 (two's complement
of the ring element); numpy's wrapping int64 arithmetic on the remote
parties IS ring-2^64 arithmetic.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from pygrid_tpu.plans.placeholder import fresh_id
from pygrid_tpu.runtime import messages as M
from pygrid_tpu.runtime.pointers import PointerTensor, _raise_if_error
from pygrid_tpu.smpc import ring as R
from pygrid_tpu.smpc.additive import AdditiveSharingTensor
from pygrid_tpu.smpc.fixed import FixedPointEncoder
from pygrid_tpu.smpc.kernels import OFFSET_BITS
from pygrid_tpu.utils.exceptions import EmptyCryptoPrimitiveStoreError


def _raw_cmd(location, op: str, args: list) -> PointerTensor:
    """Issue one remote op with explicit (possibly public-first) args."""
    resp = _raise_if_error(
        location.recv_obj_msg(
            M.TensorCommandMessage(op=op, args=args, return_id=fresh_id())
        )
    )
    return PointerTensor(
        location=location, id_at_location=resp.id_at_location, shape=resp.shape
    )


class RemoteCryptoProvider:
    """Client handle to a crypto-provider worker on the grid.

    ``location`` is anything with ``recv_obj_msg`` — a
    :class:`~pygrid_tpu.client.data_centric.DataCentricFLClient` dialed at
    the provider node, or an in-process VirtualWorker with an attached
    :class:`~pygrid_tpu.smpc.provider.CryptoProvider`. The provider deals
    per-party share arrays directly to the share-holder nodes over its own
    node mesh (reference: james in ``x.share(..., crypto_provider=james)``).

    ``auto_refill=True`` reproduces the reference client's transparent
    refill round-trip: an ``EmptyCryptoPrimitiveStoreError`` coming back
    over the wire triggers one ``provide`` request built from the error's
    kwargs, then a retry (reference ``syft_events.py:34-45``).
    """

    def __init__(self, location: Any, auto_refill: bool = True) -> None:
        self.location = location
        self.auto_refill = auto_refill

    @property
    def id(self) -> str:
        return getattr(self.location, "id", str(self.location))

    def provide(
        self,
        op: str,
        shape_x: Sequence[int],
        shape_y: Sequence[int],
        n_parties: int,
        n_instances: int = 1,
    ) -> None:
        """The refill request (reference's provide-primitives round)."""
        self.location.recv_obj_msg(
            M.CryptoProvideMessage(
                op=op,
                shape_x=list(shape_x),
                shape_y=list(shape_y),
                n_parties=int(n_parties),
                n_instances=int(n_instances),
            )
        )

    def _request(self, msg: M.CryptoRequestMessage) -> M.CryptoDealResponse:
        try:
            return _raise_if_error(self.location.recv_obj_msg(msg))
        except EmptyCryptoPrimitiveStoreError as err:
            if not self.auto_refill:
                raise
            kw = err.kwargs_
            self.provide(
                kw.get("op", msg.op),
                kw.get("shapes", [msg.shape_x, msg.shape_y])[0],
                kw.get("shapes", [msg.shape_x, msg.shape_y])[1],
                kw.get("n_parties", len(msg.party_ids)),
                kw.get("n_instances", 1),
            )
            return _raise_if_error(self.location.recv_obj_msg(msg))

    def deal(
        self,
        op: str,
        shape_x: Sequence[int],
        shape_y: Sequence[int],
        parties: Sequence[Any],
    ) -> list[list[PointerTensor]]:
        """Deal one primitive; returns per-component pointer lists
        (``[component][party]``) addressed through the caller's own
        connections to the party nodes."""
        party_ids = [getattr(p, "id", str(p)) for p in parties]
        resp = self._request(
            M.CryptoRequestMessage(
                op=op,
                shape_x=list(shape_x),
                shape_y=list(shape_y),
                party_ids=party_ids,
            )
        )
        sx, sy = tuple(shape_x), tuple(shape_y)
        if op == "matmul":
            shapes = [sx, sy, sx[:-1] + sy[1:]]
        elif op == "trunc":
            shapes = [sx, sx]  # [r], [r/scale] both carry the value shape
        else:
            shapes = [sx, sy, np.broadcast_shapes(sx, sy)]
        n_components = len(resp.ids[0])
        return [
            [
                PointerTensor(
                    location=parties[i],
                    id_at_location=resp.ids[i][k],
                    shape=shapes[k],
                )
                for i in range(len(parties))
            ]
            for k in range(n_components)
        ]


class RemoteSharedTensor:
    """Handle to a secret whose additive shares live on remote nodes.

    ``pointers[i]`` points at owner i's int64 share array. Linear ops are
    share-local (one remote op per node, no cross-node traffic);
    multiplicative ops run the Beaver round over the grid with a
    :class:`RemoteCryptoProvider`; ``get()`` opens the secret by fetching
    and summing all shares."""

    def __init__(
        self,
        pointers: list,
        encoder: FixedPointEncoder | None,
        provider: RemoteCryptoProvider | None = None,
    ) -> None:
        self.pointers = list(pointers)
        self.encoder = encoder
        self.provider = provider

    @property
    def n_parties(self) -> int:
        return len(self.pointers)

    @property
    def locations(self) -> list:
        return [p.location for p in self.pointers]

    @property
    def shape(self) -> tuple:
        return tuple(self.pointers[0].shape or ())

    # --- open ---------------------------------------------------------------

    def get(self, delete: bool = True) -> np.ndarray:
        """Fetch every share, sum in the ring, decode."""
        shares = [
            np.asarray(p.get(delete=delete)).astype(np.int64)
            for p in self.pointers
        ]
        total = R.to_ring(sum_int64_wrapping(shares).astype(np.uint64))
        if self.encoder:
            return self.encoder.decode(total)
        return R.from_ring_signed(total)

    # --- share-local linear algebra (additive homomorphism) ----------------

    def _party_ids(self) -> list:
        return [getattr(p.location, "id", id(p.location)) for p in self.pointers]

    def _zip_op(self, other: "RemoteSharedTensor", op: str):
        if self._party_ids() != other._party_ids():
            raise ValueError(
                "operands are shared over different parties: "
                f"{self._party_ids()} vs {other._party_ids()}"
            )
        mine, theirs = self.encoder, other.encoder
        if (mine is None) != (theirs is None) or (
            mine is not None and mine.scale != theirs.scale
        ):
            raise ValueError("mismatched fixed-point encoders")
        ptrs = [
            getattr(a, op)(b)
            for a, b in zip(self.pointers, other.pointers)
        ]
        return RemoteSharedTensor(
            ptrs, self.encoder, self.provider or other.provider
        )

    def __add__(self, other: "RemoteSharedTensor") -> "RemoteSharedTensor":
        return self._zip_op(other, "__add__")

    def __sub__(self, other: "RemoteSharedTensor") -> "RemoteSharedTensor":
        return self._zip_op(other, "__sub__")

    def mul_public(self, c: int) -> "RemoteSharedTensor":
        """Multiply by a public integer (share-local; no rescale, so for
        fixed-point secrets ``c`` must be an integer scalar)."""
        if not float(c).is_integer():
            raise ValueError("public factor must be an integer")
        ptrs = [p * np.int64(int(c)) for p in self.pointers]
        return RemoteSharedTensor(ptrs, self.encoder, self.provider)

    # --- multiplicative ops: Beaver over the grid protocol ------------------

    def __mul__(self, other) -> "RemoteSharedTensor":
        if isinstance(other, RemoteSharedTensor):
            return self._beaver_remote(other, "mul")
        return self.mul_public(other)

    def __matmul__(self, other) -> "RemoteSharedTensor":
        if not isinstance(other, RemoteSharedTensor):
            raise TypeError("matmul with public operands: share the public side")
        return self._beaver_remote(other, "matmul")

    def _beaver_remote(
        self, other: "RemoteSharedTensor", op: str
    ) -> "RemoteSharedTensor":
        """One Beaver round across real nodes.

        The provider node deals triple shares [a],[b],[c] directly to each
        share-holder; the masked differences d = x−a, e = y−b are opened
        (they are uniform — opening them is the protocol, not a leak); each
        node combines share-locally; only party 0 folds in the public d∘e.
        Fixed-point products then rescale via mask-and-open truncation —
        at no point does any single node (provider included) hold the
        secret. Mirrors reference test_basic_syft_operations.py:455-491.
        """
        if self._party_ids() != other._party_ids():
            raise ValueError(
                "operands are shared over different parties: "
                f"{self._party_ids()} vs {other._party_ids()}"
            )
        if (self.encoder is None) != (other.encoder is None) or (
            self.encoder is not None
            and self.encoder.scale != other.encoder.scale
        ):
            raise ValueError("mismatched fixed-point encoders")
        provider = self.provider or other.provider
        if provider is None:
            raise ValueError("this operation requires a crypto_provider")
        ring = np.int64  # shares/masks travel as wrapping int64
        combine = (
            (lambda u, v: u * v) if op == "mul" else (lambda u, v: u @ v)
        )

        a_ptrs, b_ptrs, c_ptrs = provider.deal(
            op, self.shape, other.shape, self.locations
        )
        # share-local masking, then open the (uniform) masked differences
        d = _open_pointers(
            [x - a for x, a in zip(self.pointers, a_ptrs)]
        ).astype(ring)
        e = _open_pointers(
            [y - b for y, b in zip(other.pointers, b_ptrs)]
        ).astype(ring)
        with np.errstate(over="ignore"):
            de = combine(d, e)
        z_ptrs = []
        for i, (a, b, c) in enumerate(zip(a_ptrs, b_ptrs, c_ptrs)):
            loc = c.location
            if op == "mul":
                db = b * d  # share-local: public d ∘ [b]_i
                ae = a * e
            else:
                db = _raw_cmd(loc, "__matmul__", [d, M.ref(b.id_at_location)])
                ae = a @ e
            t = c + db
            z = t + ae
            if i == 0:
                zd = z + de
                z.delete()
                z = zd
            for tmp in (a, b, c, db, ae, t):
                tmp.delete()
            z_ptrs.append(z)
        if self.encoder is not None:
            z_ptrs = self._truncate_remote(z_ptrs, provider)
        return RemoteSharedTensor(z_ptrs, self.encoder, provider)

    def _truncate_remote(
        self, z_ptrs: list, provider: RemoteCryptoProvider
    ) -> list:
        """Mask-and-open rescale of product shares by the encoder scale —
        the wire twin of :func:`pygrid_tpu.smpc.kernels.masked_truncate`
        (same pair, same offset, ε ∈ {0,1} ULP error; no node sees the
        product, the client sees only the masked open)."""
        scale = self.encoder.scale
        shape = tuple(z_ptrs[0].shape or ())
        locations = [p.location for p in z_ptrs]
        r_ptrs, rp_ptrs = provider.deal("trunc", shape, [scale], locations)
        offset = int(scale) << OFFSET_BITS
        m_ptrs = []
        for i, (z, r) in enumerate(zip(z_ptrs, r_ptrs)):
            m = z + r
            if i == 0:
                mo = m + np.int64(offset)
                m.delete()
                m = mo
            z.delete()
            r.delete()
            m_ptrs.append(m)
        m = _open_pointers(m_ptrs)  # masked: z + scale·2^30 + r, < 2^63
        q_minus = (m.astype(np.uint64) // np.uint64(scale)).astype(
            np.int64
        ) - np.int64(1 << OFFSET_BITS)
        out = []
        for i, rp in enumerate(rp_ptrs):
            if i == 0:
                out.append(
                    _raw_cmd(
                        rp.location, "__sub__", [q_minus, M.ref(rp.id_at_location)]
                    )
                )
                rp.delete()
            else:
                out.append(-rp)
                rp.delete()
        return out

    def __repr__(self) -> str:
        locs = [getattr(loc, "id", loc) for loc in self.locations]
        return f"RemoteSharedTensor(parties={locs})"


def _open_pointers(ptrs: Sequence[PointerTensor]) -> np.ndarray:
    """Fetch and ring-sum a set of share pointers (consumes the objects)."""
    return sum_int64_wrapping(
        [np.asarray(p.get()).astype(np.int64) for p in ptrs]
    )


def sum_int64_wrapping(arrays: Sequence[np.ndarray]) -> np.ndarray:
    """Ring sum of int64 share arrays (numpy wraps on overflow — exactly
    the mod-2^64 semantics the shares need)."""
    with np.errstate(over="ignore"):
        total = arrays[0].copy()
        for a in arrays[1:]:
            total += a
    return total


def share_to_nodes(
    x: np.ndarray,
    clients: Sequence[Any],
    encoder: FixedPointEncoder | None = None,
    tags: Sequence[str] = (),
    crypto_provider: RemoteCryptoProvider | Any | None = None,
) -> RemoteSharedTensor:
    """Split ``x`` into len(clients) additive shares, one per node.

    ``clients``: DataCentricFLClient-like locations (anything pointers can
    ``send`` through). ``crypto_provider``: a :class:`RemoteCryptoProvider`
    (or a bare provider-node location, which is wrapped) enabling Beaver
    mul/matmul. Mirrors the reference's
    ``x.fix_prec().share(*nodes, crypto_provider=james)``."""
    owners = [getattr(c, "id", str(i)) for i, c in enumerate(clients)]
    ast = AdditiveSharingTensor.share(
        np.asarray(x), owners, encoder=encoder
    )
    from pygrid_tpu.runtime.pointers import send as _send

    share_arrays = R.from_ring(ast.shares).astype(np.int64)  # [P, ...]
    pointers = []
    for i, client in enumerate(clients):
        pointers.append(_send(share_arrays[i], client, tags=set(tags)))
    if crypto_provider is not None and not isinstance(
        crypto_provider, RemoteCryptoProvider
    ):
        crypto_provider = RemoteCryptoProvider(crypto_provider)
    return RemoteSharedTensor(pointers, encoder, crypto_provider)


def fix_prec_share_to_nodes(
    x: np.ndarray,
    clients: Sequence[Any],
    base: int = 10,
    precision_fractional: int = 3,
    tags: Sequence[str] = (),
    crypto_provider: RemoteCryptoProvider | Any | None = None,
) -> RemoteSharedTensor:
    """``x.fix_prec().share(alice, bob, …, crypto_provider=james)`` over
    real nodes."""
    encoder = FixedPointEncoder(base, precision_fractional)
    return share_to_nodes(
        x, clients, encoder=encoder, tags=tags, crypto_provider=crypto_provider
    )
