"""Ring-2^64 arithmetic on TPU as paired uint32 limbs.

The syft-0.2.9 ``AdditiveSharingTensor`` the reference depends on (consumed at
reference ``routes/data_centric/routes.py:215-236`` and exercised by
``tests/data_centric/test_basic_syft_operations.py:383-491``) does its ring
arithmetic in torch int64 with native wraparound. TPUs have no 64-bit integer
units, so here a ring element is a :class:`Ring64` pytree of two uint32 arrays
``(lo, hi)`` and every op is built from 32-bit limb arithmetic:

- add/sub/neg: limb add with carry (uint32 wraparound is well-defined in XLA);
- mul: 32x32→64 via 16-bit half-limbs;
- matmul: 8-bit limb decomposition into int32 ``dot_general``s (exact for
  contraction K ≤ 2^15 per chunk; longer K is scanned in chunks) recombined
  with shifted carries — see :func:`ring_matmul`;
- division by a small public constant (fixed-point truncation): 16-bit-limb
  long division.

Everything is jit/vmap-safe and shape-polymorphic over leading axes, so a
batch of SMPC parties is just a leading array axis (SURVEY.md §2.5).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

U32 = jnp.uint32
_MASK16 = np.uint32(0xFFFF)


class Ring64(NamedTuple):
    """One ring element per array position: value = hi * 2^32 + lo (mod 2^64)."""

    lo: jax.Array  # uint32
    hi: jax.Array  # uint32

    @property
    def shape(self):
        return self.lo.shape

    def __add__(self, other):
        return ring_add(self, other)

    def __sub__(self, other):
        return ring_sub(self, other)

    def __neg__(self):
        return ring_neg(self)

    def __mul__(self, other):
        return ring_mul(self, other)

    def __matmul__(self, other):
        return ring_matmul(self, other)


# --- host <-> ring conversion (numpy, exact via int64/uint64) ---------------


def to_ring(x: np.ndarray) -> Ring64:
    """Host integers (any int dtype, values taken mod 2^64) -> Ring64."""
    v = np.asarray(x).astype(np.uint64)
    return Ring64(
        lo=jnp.asarray((v & np.uint64(0xFFFFFFFF)).astype(np.uint32)),
        hi=jnp.asarray((v >> np.uint64(32)).astype(np.uint32)),
    )


def from_ring(r: Ring64) -> np.ndarray:
    """Ring64 -> host uint64 (exact)."""
    lo = np.asarray(r.lo).astype(np.uint64)
    hi = np.asarray(r.hi).astype(np.uint64)
    return (hi << np.uint64(32)) | lo


def from_ring_signed(r: Ring64) -> np.ndarray:
    """Ring64 -> host int64, two's-complement interpretation (exact)."""
    return from_ring(r).astype(np.int64)


def ring_zeros(shape) -> Ring64:
    return Ring64(jnp.zeros(shape, U32), jnp.zeros(shape, U32))


def ring_from_u32(lo: jax.Array) -> Ring64:
    return Ring64(lo.astype(U32), jnp.zeros_like(lo, U32))


# --- elementwise ring ops ---------------------------------------------------


def ring_add(a: Ring64, b: Ring64) -> Ring64:
    lo = a.lo + b.lo
    carry = (lo < a.lo).astype(U32)
    return Ring64(lo, a.hi + b.hi + carry)


def ring_neg(a: Ring64) -> Ring64:
    # two's complement: ~a + 1. The +1 carries into hi exactly when lo == 0
    # (~lo + 1 wraps to 0 only then).
    lo = ~a.lo + U32(1)
    carry = (a.lo == 0).astype(U32)
    return Ring64(lo, ~a.hi + carry)


def ring_sub(a: Ring64, b: Ring64) -> Ring64:
    return ring_add(a, ring_neg(b))


def _mul_u32(a: jax.Array, b: jax.Array) -> tuple[jax.Array, jax.Array]:
    """32x32 -> (lo32, hi32) exact product via 16-bit half-limbs."""
    a_lo, a_hi = a & _MASK16, a >> 16
    b_lo, b_hi = b & _MASK16, b >> 16
    ll = a_lo * b_lo  # < 2^32, exact in u32
    lh = a_lo * b_hi  # < 2^32
    hl = a_hi * b_lo
    hh = a_hi * b_hi
    # lo = ll + ((lh + hl) << 16)  with carries into hi
    mid = lh + hl
    mid_carry = (mid < lh).astype(U32)  # overflow of the u32 add
    lo = ll + (mid << 16)
    lo_carry = (lo < ll).astype(U32)
    hi = hh + (mid >> 16) + (mid_carry << 16) + lo_carry
    return lo, hi


def ring_mul(a: Ring64, b: Ring64) -> Ring64:
    """Elementwise 64x64 -> low 64 bits."""
    lo, hi = _mul_u32(a.lo, b.lo)
    hi = hi + a.lo * b.hi + a.hi * b.lo  # wrap mod 2^32 is correct here
    return Ring64(lo, hi)


def ring_mul_const(a: Ring64, c: int) -> Ring64:
    return ring_mul(a, to_ring(np.uint64(c % (1 << 64))))


# --- exact ring matmul via 8-bit limb dot_generals --------------------------

_CHUNK_K = 1 << 14  # int32 accumulator holds K * 255^2 exactly for K ≤ 2^15


def _to_limbs8(x_lo: jax.Array, x_hi: jax.Array) -> list[jax.Array]:
    """Split (lo, hi) uint32 pair into eight 8-bit limbs as int32 arrays."""
    limbs = []
    for word in (x_lo, x_hi):
        for s in (0, 8, 16, 24):
            limbs.append(((word >> s) & U32(0xFF)).astype(jnp.int32))
    return limbs


def _matmul_i32(a: jax.Array, b: jax.Array) -> jax.Array:
    return lax.dot_general(
        a, b, (((a.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )


def _ring_matmul_chunk(a: Ring64, b: Ring64) -> Ring64:
    """Exact [M,K]@[K,N] over the ring for K ≤ 2^15."""
    a_limbs = _to_limbs8(a.lo, a.hi)  # 8 limbs, int32 in [0, 255]
    b_limbs = _to_limbs8(b.lo, b.hi)
    out_shape = a.lo.shape[:-1] + b.lo.shape[1:]
    # Partial product P_ij (exact: ≤ K*255^2 < 2^31) contributes at bit
    # offset 8*(i+j); offsets ≥ 64 vanish mod 2^64. Summing partials of equal
    # offset *before* the ring add could overflow int32, so each P folds into
    # the u64 accumulator individually.
    acc = ring_zeros(out_shape)
    for i in range(8):
        for j in range(8 - i):
            p = _matmul_i32(a_limbs[i], b_limbs[j]).astype(U32)
            acc = ring_add(acc, _shift_left_u64(p, 8 * (i + j)))
    return acc


def _shift_left_u64(p_u32: jax.Array, off: int) -> Ring64:
    """(u32 value) << off as a Ring64, off in [0, 64)."""
    if off == 0:
        return Ring64(p_u32, jnp.zeros_like(p_u32))
    if off < 32:
        lo = p_u32 << off
        hi = p_u32 >> (32 - off)
        return Ring64(lo, hi)
    return Ring64(jnp.zeros_like(p_u32), p_u32 << (off - 32))


#: tri-state Pallas dispatch override: None = env/platform default
_PALLAS_ENABLED: bool | None = None


def set_pallas_enabled(enabled: bool | None) -> None:
    """Runtime kill-switch for the Pallas matmul dispatch.

    The dispatch decision is read at **trace time**, so flipping it must
    also drop cached executables — this clears the jit caches so every
    already-traced shape retraces with the new setting."""
    global _PALLAS_ENABLED
    _PALLAS_ENABLED = enabled
    jax.clear_caches()


def _pallas_eligible(a: Ring64, b: Ring64) -> bool:
    import os

    if _PALLAS_ENABLED is not None:
        if not _PALLAS_ENABLED:
            return False
    elif os.environ.get("PYGRID_TPU_NO_PALLAS"):
        # env read at trace time: set it before first use, or use
        # set_pallas_enabled() to flip a live process
        return False
    if a.lo.ndim != 2 or b.lo.ndim != 2:
        return False
    try:
        import jax.extend.backend

        platform = jax.extend.backend.get_backend().platform
    except Exception:  # noqa: BLE001 — backend probing is best-effort
        return False
    return platform in ("tpu", "axon")


def ring_matmul(a: Ring64, b: Ring64) -> Ring64:
    """Exact matmul over Z_2^64: a [..M, K] @ b [K, N..].

    On TPU, 2-D contractions go through the fused Pallas kernel
    (:mod:`pygrid_tpu.smpc.pallas_kernels`, ~7× the XLA limb path;
    opt out with ``PYGRID_TPU_NO_PALLAS=1``). Elsewhere the contraction is
    chunked so each int32 ``dot_general`` stays exact; chunks are folded
    with ring adds. XLA maps the int32 dots onto the MXU/VPU and fuses the
    limb recombination.
    """
    if _pallas_eligible(a, b):
        from pygrid_tpu.smpc.pallas_kernels import pallas_ring_matmul

        return pallas_ring_matmul(a, b)
    k = a.lo.shape[-1]
    if k <= _CHUNK_K:
        return _ring_matmul_chunk(a, b)
    n_chunks = -(-k // _CHUNK_K)
    pad = n_chunks * _CHUNK_K - k
    a_lo = jnp.pad(a.lo, [(0, 0)] * (a.lo.ndim - 1) + [(0, pad)])
    a_hi = jnp.pad(a.hi, [(0, 0)] * (a.hi.ndim - 1) + [(0, pad)])
    b_lo = jnp.pad(b.lo, [(0, pad)] + [(0, 0)] * (b.lo.ndim - 1))
    b_hi = jnp.pad(b.hi, [(0, pad)] + [(0, 0)] * (b.hi.ndim - 1))
    out = None
    for c in range(n_chunks):
        sl = slice(c * _CHUNK_K, (c + 1) * _CHUNK_K)
        part = _ring_matmul_chunk(
            Ring64(a_lo[..., sl], a_hi[..., sl]),
            Ring64(b_lo[sl], b_hi[sl]),
        )
        out = part if out is None else ring_add(out, part)
    return out


# --- division by a small public constant (for fixed-point truncation) -------


def ring_div_const(a: Ring64, d: int) -> Ring64:
    """Exact unsigned division of each ring element by constant d < 2^16.

    16-bit-limb long division: remainders stay < d < 2^16 so every
    intermediate fits in uint32.
    """
    if not 0 < d < (1 << 16):
        raise ValueError("ring_div_const requires 0 < d < 2^16")
    dd = U32(d)
    limbs = [
        (a.hi >> 16) & _MASK16,
        a.hi & _MASK16,
        (a.lo >> 16) & _MASK16,
        a.lo & _MASK16,
    ]
    rem = jnp.zeros_like(a.lo)
    qs = []
    for limb in limbs:
        cur = (rem << 16) | limb  # rem < d ≤ 2^16-1 → cur < 2^32
        qs.append(cur // dd)
        rem = cur % dd
    q_hi = (qs[0] << 16) | qs[1]
    q_lo = (qs[2] << 16) | qs[3]
    return Ring64(q_lo, q_hi)


def ring_div_const_signed(a: Ring64, d: int) -> Ring64:
    """Signed (two's-complement) division by small constant, rounding toward
    zero — matches torch integer division used by the reference stack."""
    neg = a.hi >> 31  # sign bit
    abs_a = Ring64(
        jnp.where(neg.astype(bool), ring_neg(a).lo, a.lo),
        jnp.where(neg.astype(bool), ring_neg(a).hi, a.hi),
    )
    q = ring_div_const(abs_a, d)
    nq = ring_neg(q)
    return Ring64(
        jnp.where(neg.astype(bool), nq.lo, q.lo),
        jnp.where(neg.astype(bool), nq.hi, q.hi),
    )


# --- random ring elements ---------------------------------------------------


def ring_random(key: jax.Array, shape) -> Ring64:
    k1, k2 = jax.random.split(key)
    # randint over the full uint32 range
    lo = jax.random.bits(k1, shape, dtype=jnp.uint32)
    hi = jax.random.bits(k2, shape, dtype=jnp.uint32)
    return Ring64(lo, hi)


# --- collective ring sum (the mesh-sharded "open") ---------------------------


def ring_psum(
    r: Ring64, axis_name: str, local_axis: int | None = 0
) -> Ring64:
    """Exact sum mod 2^64 over ``local_axis`` *and* the mesh axis
    ``axis_name`` — the collective "open" for shares sharded over a party
    mesh axis (call inside ``shard_map``).

    A plain ``psum`` of the (lo, hi) u32 limbs would drop inter-limb
    carries (carry propagation is not linear, so it cannot ride the
    collective). Instead each 64-bit share splits into four 16-bit
    half-limbs held in u32; those sums are carry-free for up to 2^16
    parties (limb sum ≤ P·(2^16−1) < 2^32), so the psum is exact, and the
    carries are propagated once, locally, after the collective.
    """
    limbs = [
        r.lo & _MASK16,
        r.lo >> 16,
        r.hi & _MASK16,
        r.hi >> 16,
    ]
    if local_axis is not None:
        limbs = [l.sum(axis=local_axis, dtype=U32) for l in limbs]
    limbs = [lax.psum(l, axis_name) for l in limbs]
    out, carry = [], None
    for l in limbs:
        c = l if carry is None else l + carry
        out.append(c & _MASK16)
        carry = c >> 16
    return Ring64(out[0] | (out[1] << 16), out[2] | (out[3] << 16))
