"""Batched, jitted SMPC kernels — the performance path.

The class API in :mod:`pygrid_tpu.smpc.additive` is the protocol-faithful,
numpy-facing surface. These functions are its pure-XLA core: everything is a
function of stacked ring arrays, jit-compiled once and ``vmap``-ed over a
batch axis so one chip runs B independent SMPC instances (B×P virtual
parties) per launch — the TPU-native answer to the reference's
one-process-per-party grid (SURVEY.md §2.5, BASELINE.md north star).

Layouts: shares are ``Ring64`` with leading axes ``[B?, P, ...]`` where P is
the party axis. "Opening" a masked value is a sum over P — the mesh-sharded
variant of these kernels (:mod:`pygrid_tpu.smpc.sharded`) puts P on a
``Mesh`` axis via ``shard_map`` and opens with an exact collective
(:func:`pygrid_tpu.smpc.ring.ring_psum`) instead of socket traffic.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from pygrid_tpu.smpc import ring as R


def share_kernel(key: jax.Array, value: R.Ring64, n_parties: int) -> R.Ring64:
    """Split a ring tensor into P additive shares, stacked on axis 0."""
    keys = jax.random.split(key, n_parties - 1)
    rand_lo, rand_hi, total = [], [], None
    for k in keys:
        r = R.ring_random(k, value.shape)
        rand_lo.append(r.lo)
        rand_hi.append(r.hi)
        total = r if total is None else R.ring_add(total, r)
    last = R.ring_sub(value, total)
    return R.Ring64(
        jnp.stack(rand_lo + [last.lo]), jnp.stack(rand_hi + [last.hi])
    )


def reconstruct_kernel(shares: R.Ring64) -> R.Ring64:
    """Sum over the party axis (axis 0). With shares sharded over a mesh
    party axis this is the collective 'open'."""
    total = R.Ring64(shares.lo[0], shares.hi[0])
    for i in range(1, shares.lo.shape[0]):
        total = R.ring_add(total, R.Ring64(shares.lo[i], shares.hi[i]))
    return total


def _party_map(fn, *stacked: R.Ring64) -> R.Ring64:
    """vmap a ring fn over the party axis of stacked shares."""
    return jax.vmap(fn)(*stacked)


def beaver_combine(
    x_sh: R.Ring64,
    y_sh: R.Ring64,
    a_sh: R.Ring64,
    b_sh: R.Ring64,
    c_sh: R.Ring64,
    op: str,
) -> R.Ring64:
    """One full Beaver round on stacked shares [P, ...] -> product shares.

    z_i = c_i + d∘b_i + a_i∘e + [i=0] d∘e,  d = open(x−a), e = open(y−b).
    """
    ring_op = R.ring_mul if op == "mul" else R.ring_matmul
    d = reconstruct_kernel(R.ring_sub(x_sh, a_sh))
    e = reconstruct_kernel(R.ring_sub(y_sh, b_sh))
    db = _party_map(lambda b: ring_op(d, b), b_sh)
    ae = _party_map(lambda a: ring_op(a, e), a_sh)
    z = R.ring_add(c_sh, R.ring_add(db, ae))
    de = ring_op(d, e)
    z0 = R.ring_add(R.Ring64(z.lo[0], z.hi[0]), de)
    return R.Ring64(z.lo.at[0].set(z0.lo), z.hi.at[0].set(z0.hi))


#: mask-and-open truncation offset magnitude: the secret product z must
#: satisfy |z| < scale * 2^OFFSET_BITS, i.e. |x·y| < 2^OFFSET_BITS / scale
OFFSET_BITS = 30


def masked_truncate(
    z_sh: R.Ring64, r_sh: R.Ring64, rp_sh: R.Ring64, scale: int
) -> R.Ring64:
    """Rescale product shares by ``scale`` without anyone seeing the secret.

    Mask-and-open truncation with a dealer-provided pair
    (``r`` uniform < 2^62, ``r' = floor(r/scale)``):

    1. open ``m = z + OFFSET + r``  (OFFSET = scale·2^30 keeps the sum
       positive; m < 2^63 so the ring sum is the exact integer sum);
    2. publicly compute ``q = floor(m / scale)``;
    3. output shares: party 0 holds ``q − 2^30 − r'_0``, party i>0 holds
       ``−r'_i``  →  the shares sum to ``floor(z/scale) + ε``, ε ∈ {0, 1}.

    Nobody learns z: parties only ever see their own shares, and the opened
    ``m`` is statistically masked by r (distance ≈ 2^(log2(scale)+31−62)).
    Compare the dealer-sees-all alternative
    :meth:`~pygrid_tpu.smpc.provider.CryptoProvider.reshare_truncated`,
    which reconstructs z at the dealer (reference-faithful exactness, kept
    behind ``trusted_dealer=True``).
    """
    import numpy as np

    offset = R.to_ring(np.uint64(scale) << np.uint64(OFFSET_BITS))
    m_sh = R.ring_add(z_sh, r_sh)
    m0 = R.ring_add(R.Ring64(m_sh.lo[0], m_sh.hi[0]), offset)
    m_sh = R.Ring64(m_sh.lo.at[0].set(m0.lo), m_sh.hi.at[0].set(m0.hi))
    m = reconstruct_kernel(m_sh)  # public masked value, < 2^63
    q = R.ring_div_const(m, scale)
    out = _party_map(R.ring_neg, rp_sh)  # party i: −r'_i
    head = R.ring_add(
        R.Ring64(out.lo[0], out.hi[0]),
        R.ring_sub(q, R.to_ring(np.uint64(1) << np.uint64(OFFSET_BITS))),
    )
    return R.Ring64(
        out.lo.at[0].set(head.lo), out.hi.at[0].set(head.hi)
    )


@partial(jax.jit, static_argnames=("op", "n_parties"))
def batched_beaver(
    key: jax.Array,
    x_sh: R.Ring64,
    y_sh: R.Ring64,
    op: str = "matmul",
    n_parties: int = 3,
) -> R.Ring64:
    """B independent Beaver rounds, triples generated on-chip.

    ``x_sh``/``y_sh``: shares with leading axes [B, P, ...]. The triple
    dealer runs inside the same XLA program (trusted-dealer simulation), so
    the whole round — deal, mask, open, combine — is one launch.
    """
    ring_op = R.ring_mul if op == "mul" else R.ring_matmul
    B = x_sh.lo.shape[0]

    def one(bkey, x1, y1):
        k1, k2, k3 = jax.random.split(bkey, 3)
        a = R.ring_random(k1, x1.lo.shape[1:])
        b = R.ring_random(k2, y1.lo.shape[1:])
        c = ring_op(a, b)
        a_sh = share_kernel(k3, a, n_parties)
        b_sh = share_kernel(jax.random.fold_in(k3, 1), b, n_parties)
        c_sh = share_kernel(jax.random.fold_in(k3, 2), c, n_parties)
        return beaver_combine(x1, y1, a_sh, b_sh, c_sh, op)

    keys = jax.random.split(key, B)
    return jax.vmap(one)(keys, x_sh, y_sh)
