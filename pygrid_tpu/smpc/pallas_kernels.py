"""Pallas TPU kernel for the SMPC hot op: exact uint64 ring matmul.

The Beaver-triple matmul (``smpc/kernels.py``) is the FLOP core of the
SMPC plane (SURVEY.md §7 "hard parts": no native uint64 matmul on TPU).
The XLA path in :func:`pygrid_tpu.smpc.ring.ring_matmul` materializes 16
limb arrays in HBM and runs 36 separate ``dot_general``s; this kernel fuses
the whole thing per output tile:

- 8-bit limb extraction happens in VMEM right after the block DMA,
- the 36 partial ``jnp.dot``s (limb pairs with i+j < 8) run back-to-back
  on the MXU in float32 — Mosaic has no int32 matmul on v5e; f32 products
  of 8-bit limbs summed over a ≤256 chunk stay < 2^24 so every dot is
  exact, and each is cast back to int32 before cross-pair accumulation
  (f32 would round above 2^24),
- the shifted carry recombination into (lo, hi) uint32 runs on the VPU
  while the next K-chunk streams in,

so HBM traffic is one read of A and B and one write of C instead of ~16
limb-array round-trips. Grid: (M/TM, N/TN, K/KC) with the K axis innermost
— the output tile stays resident in VMEM across K steps, accumulating with
explicit carries.

Correctness contract: identical bit-for-bit to ``ring_matmul`` (tests run
this kernel in interpret mode on CPU against the XLA path and against
numpy uint64).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pygrid_tpu.parallel.compat import tpu_compiler_params

_CompilerParams = tpu_compiler_params()


from pygrid_tpu.smpc.ring import Ring64

TILE_M = 128
TILE_N = 128
#: K-chunk per grid step; 255² × 256 = 16 646 400 < 2^24, so every f32
#: limb dot is exact — the binding constraint for the MXU path
CHUNK_K = 256


def _limbs8(lo: jax.Array, hi: jax.Array) -> list[jax.Array]:
    """Eight 8-bit limbs of a (lo, hi) uint32 pair, little-endian, as f32
    (the MXU-accepted dtype; values 0..255 are exact). Mosaic has no
    uint32→f32 cast, so the route is bitcast→int32→f32 (limbs < 2^31)."""
    from jax import lax

    mask = jnp.uint32(0xFF)

    def limb(word: jax.Array, i: int) -> jax.Array:
        raw = (word >> jnp.uint32(8 * i)) & mask
        return lax.bitcast_convert_type(raw, jnp.int32).astype(jnp.float32)

    return [limb(lo, i) for i in range(4)] + [limb(hi, i) for i in range(4)]


def _matmul_kernel(a_lo, a_hi, b_lo, b_hi, out_lo, out_hi):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        out_lo[:] = jnp.zeros_like(out_lo)
        out_hi[:] = jnp.zeros_like(out_hi)

    a_limbs = _limbs8(a_lo[:], a_hi[:])
    b_limbs = _limbs8(b_lo[:], b_hi[:])

    # partial products by output shift s = i + j (s ≥ 8 vanishes mod 2^64)
    parts = [None] * 8
    for i in range(8):
        for j in range(8 - i):
            d = jnp.dot(
                a_limbs[i], b_limbs[j], preferred_element_type=jnp.float32
            ).astype(jnp.int32)
            s = i + j
            parts[s] = d if parts[s] is None else parts[s] + d

    from jax import lax

    lo, hi = out_lo[:], out_hi[:]
    for s in range(8):
        p = lax.bitcast_convert_type(parts[s], jnp.uint32)
        shift = 8 * s
        if shift < 32:
            add_lo = p << jnp.uint32(shift) if shift else p
            add_hi = p >> jnp.uint32(32 - shift) if shift else jnp.uint32(0)
        else:
            add_lo = jnp.zeros_like(p)
            add_hi = p << jnp.uint32(shift - 32)
        new_lo = lo + add_lo
        carry = (new_lo < lo).astype(jnp.uint32)
        hi = hi + add_hi + carry
        lo = new_lo
    out_lo[:] = lo
    out_hi[:] = hi


def _pad2(x: jax.Array, rows: int, cols: int) -> jax.Array:
    pr, pc = rows - x.shape[0], cols - x.shape[1]
    if pr or pc:
        x = jnp.pad(x, ((0, pr), (0, pc)))
    return x


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


@partial(jax.jit, static_argnames=("interpret",))
def pallas_ring_matmul(a: Ring64, b: Ring64, interpret: bool = False) -> Ring64:
    """Exact ``a [M,K] @ b [K,N]`` over Z_2^64, one fused Pallas launch;
    batched ``[B,M,K] @ [B,K,N]`` operands vmap over the same kernel
    (pallas_call's batching rule turns the batch into a leading grid
    axis — the path ``smpc.kernels.batched_beaver`` drives).

    Tiles adapt downward for small operands: a 64×64 Beaver matmul under
    the fixed 128×128×256 tiling would spend ~8× its FLOPs multiplying
    zero padding (M, N and K each round up); only the lane dimension (N)
    is pinned to 128 by the hardware. Zero-padding stays exact (zero
    limbs contribute nothing). ``interpret=True`` runs the same kernel on
    CPU for tests."""
    if a.lo.ndim == 3 and b.lo.ndim == 3:
        if a.lo.shape[0] != b.lo.shape[0]:
            raise ValueError(
                f"batch mismatch: {a.lo.shape} @ {b.lo.shape}"
            )
        return jax.vmap(lambda x, y: pallas_ring_matmul(x, y, interpret))(
            a, b
        )
    if a.lo.ndim != 2 or b.lo.ndim != 2:
        raise ValueError("pallas_ring_matmul takes 2-D or 3-D operands")
    M, K = a.lo.shape
    K2, N = b.lo.shape
    if K != K2:
        raise ValueError(f"contraction mismatch: {a.lo.shape} @ {b.lo.shape}")
    tile_m = min(TILE_M, _round_up(M, 8))     # sublane multiple
    chunk_k = min(CHUNK_K, _round_up(K, 128))  # MXU contraction lanes
    Mp = pl.cdiv(M, tile_m) * tile_m
    Np = pl.cdiv(N, TILE_N) * TILE_N
    Kp = pl.cdiv(K, chunk_k) * chunk_k
    a_lo, a_hi = _pad2(a.lo, Mp, Kp), _pad2(a.hi, Mp, Kp)
    b_lo, b_hi = _pad2(b.lo, Kp, Np), _pad2(b.hi, Kp, Np)

    a_spec = pl.BlockSpec(
        (tile_m, chunk_k), lambda mi, ni, ki: (mi, ki),
        memory_space=pltpu.VMEM,
    )
    b_spec = pl.BlockSpec(
        (chunk_k, TILE_N), lambda mi, ni, ki: (ki, ni),
        memory_space=pltpu.VMEM,
    )
    o_spec = pl.BlockSpec(
        (tile_m, TILE_N), lambda mi, ni, ki: (mi, ni),
        memory_space=pltpu.VMEM,
    )
    out_shape = jax.ShapeDtypeStruct((Mp, Np), jnp.uint32)
    lo, hi = pl.pallas_call(
        _matmul_kernel,
        grid=(Mp // tile_m, Np // TILE_N, Kp // chunk_k),
        in_specs=[a_spec, a_spec, b_spec, b_spec],
        out_specs=[o_spec, o_spec],
        out_shape=[out_shape, out_shape],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(a_lo, a_hi, b_lo, b_hi)
    return Ring64(lo[:M, :N], hi[:M, :N])
