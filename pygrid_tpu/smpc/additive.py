"""AdditiveSharingTensor — additive secret shares over Z_2^64, party-batched.

Parity surface: syft-0.2.9 ``AdditiveSharingTensor`` as the reference grid
uses it (``x.fix_prec().share(alice, bob, charlie, crypto_provider=james)``,
remote add/sub/mul/matmul, ``.get()`` reconstruction —
``tests/data_centric/test_basic_syft_operations.py:383-491``; share-holder
discovery walks tensor chains down to this type at
``routes/data_centric/routes.py:215-236``).

TPU-native redesign: one AdditiveSharingTensor holds ALL parties' shares as a
single :class:`Ring64` whose leading axis is the party axis — shares are
HBM-resident and every protocol step (local share arithmetic, Beaver
combination) is one XLA program over that stacked array. "Network traffic"
between co-located simulated parties is a reduction over the party axis;
truly remote parties exchange per-party slices of the same arrays over the
grid protocol (pygrid_tpu.node), so the math here is transport-agnostic.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pygrid_tpu.serde import register_serde
from pygrid_tpu.smpc import ring as R
from pygrid_tpu.smpc.fixed import FixedPointEncoder
from pygrid_tpu.smpc.kernels import reconstruct_kernel, share_kernel
from pygrid_tpu.smpc.provider import CryptoProvider


def _stack_slice(shares: R.Ring64, i: int) -> R.Ring64:
    return R.Ring64(shares.lo[i], shares.hi[i])


@register_serde(name="pygrid.AdditiveSharingTensor")
class AdditiveSharingTensor:
    """Stacked additive shares. ``shares.lo/hi`` shape: [n_parties, *shape]."""

    def __init__(
        self,
        shares: R.Ring64,
        owners: Sequence[str],
        encoder: FixedPointEncoder | None = None,
        crypto_provider: CryptoProvider | None = None,
    ) -> None:
        self.shares = shares
        self.owners = tuple(owners)
        self.encoder = encoder
        self.crypto_provider = crypto_provider
        #: survives serde even when the live provider object doesn't — the
        #: encrypted-model discovery path reports it (reference
        #: routes/data_centric/routes.py:215-236)
        self.crypto_provider_id: str | None = (
            crypto_provider.id if crypto_provider is not None else None
        )

    # --- construction -------------------------------------------------------

    @classmethod
    def share(
        cls,
        x: np.ndarray,
        owners: Sequence[str],
        crypto_provider: CryptoProvider | None = None,
        encoder: FixedPointEncoder | None = None,
        key: jax.Array | None = None,
    ) -> "AdditiveSharingTensor":
        """Encode (if an encoder is given) and split into len(owners) shares."""
        n = len(owners)
        if n < 2:
            raise ValueError("need at least 2 parties")
        if key is None:
            # share secrecy rests on this randomness: full-width OS entropy,
            # not a 31-bit np.random seed an adversary could enumerate
            import secrets

            key = jax.random.PRNGKey(secrets.randbits(63))
        value = encoder.encode(x) if encoder else R.to_ring(np.asarray(x))
        return cls(share_kernel(key, value, n), owners, encoder, crypto_provider)

    @property
    def n_parties(self) -> int:
        return len(self.owners)

    @property
    def shape(self) -> tuple:
        return self.shares.lo.shape[1:]

    # --- reconstruction -----------------------------------------------------

    def reconstruct_ring(self) -> R.Ring64:
        return reconstruct_kernel(self.shares)

    def get(self) -> np.ndarray:
        """Open the secret (syft ``.get()`` then ``.float_prec()``)."""
        total = self.reconstruct_ring()
        if self.encoder:
            return self.encoder.decode(total)
        return R.from_ring_signed(total)

    # --- linear ops (share-local, no communication) -------------------------

    def _like(self, shares: R.Ring64) -> "AdditiveSharingTensor":
        return AdditiveSharingTensor(
            shares, self.owners, self.encoder, self.crypto_provider
        )

    def _check_compat(self, other: "AdditiveSharingTensor") -> None:
        if self.owners != other.owners:
            raise ValueError("shares live on different parties")
        if (self.encoder is None) != (other.encoder is None) or (
            self.encoder
            and other.encoder
            and self.encoder.scale != other.encoder.scale
        ):
            raise ValueError("mismatched fixed-point encoders")

    def __add__(self, other):
        if isinstance(other, AdditiveSharingTensor):
            self._check_compat(other)
            return self._like(R.ring_add(self.shares, other.shares))
        return self._add_public(other)

    def __sub__(self, other):
        if isinstance(other, AdditiveSharingTensor):
            self._check_compat(other)
            return self._like(R.ring_sub(self.shares, other.shares))
        return self._add_public(-np.asarray(other))

    def _add_public(self, c: np.ndarray) -> "AdditiveSharingTensor":
        """Add a public constant: only party 0's share moves."""
        enc = self.encoder.encode(c) if self.encoder else R.to_ring(np.asarray(c))
        first = R.ring_add(_stack_slice(self.shares, 0), enc)
        lo = self.shares.lo.at[0].set(first.lo)
        hi = self.shares.hi.at[0].set(first.hi)
        return self._like(R.Ring64(lo, hi))

    # --- multiplicative ops (Beaver triples) --------------------------------

    def _provider(self) -> CryptoProvider:
        if self.crypto_provider is None:
            raise ValueError("this operation requires a crypto_provider")
        return self.crypto_provider

    def _beaver(self, other: "AdditiveSharingTensor", op: str):
        """Beaver protocol round — delegates to the stacked XLA kernel."""
        from pygrid_tpu.smpc.kernels import beaver_combine, masked_truncate

        self._check_compat(other)
        provider = self._provider()
        n = self.n_parties
        a_sh, b_sh, c_sh = provider.triple(op, self.shape, other.shape, n)
        z = beaver_combine(self.shares, other.shares, a_sh, b_sh, c_sh, op)
        if self.encoder:  # product carries scale^2 — rescale once
            if provider.trusted_dealer:
                z = provider.reshare_truncated(z, self.encoder.scale, n)
            else:
                r_sh, rp_sh = provider.trunc_pair(
                    z.shape[1:], self.encoder.scale, n
                )
                z = masked_truncate(z, r_sh, rp_sh, self.encoder.scale)
        return self._like(z)

    def __mul__(self, other):
        if isinstance(other, AdditiveSharingTensor):
            return self._beaver(other, "mul")
        return self._mul_public(other)

    def __matmul__(self, other):
        if isinstance(other, AdditiveSharingTensor):
            return self._beaver(other, "matmul")
        raise TypeError("matmul with public operands: share the public side")

    def _mul_public(self, c) -> "AdditiveSharingTensor":
        """Multiply by a public integer scalar or array (share-local)."""
        c_arr = np.asarray(c)
        if not np.all(np.equal(np.mod(c_arr, 1), 0)):
            raise TypeError(
                "public multiplier must be integer-valued (fixed-point "
                "floats must be shared or encoded first)"
            )
        ring_c = R.to_ring(c_arr.astype(np.int64).astype(np.uint64))
        z = R.ring_mul(self.shares, ring_c)  # broadcasts over the party axis
        return self._like(z)

    # --- serde --------------------------------------------------------------

    def _bufferize(self) -> dict:
        return {
            "lo": np.asarray(self.shares.lo),
            "hi": np.asarray(self.shares.hi),
            "owners": list(self.owners),
            "base": self.encoder.base if self.encoder else None,
            "precision": self.encoder.precision_fractional if self.encoder else None,
            "crypto_provider_id": self.crypto_provider_id,
        }

    @classmethod
    def _unbufferize(cls, data: dict) -> "AdditiveSharingTensor":
        encoder = None
        if data["base"] is not None:
            encoder = FixedPointEncoder(data["base"], data["precision"])
        out = cls(
            R.Ring64(jnp.asarray(data["lo"]), jnp.asarray(data["hi"])),
            data["owners"],
            encoder,
        )
        out.crypto_provider_id = data.get("crypto_provider_id")
        return out

    def __repr__(self) -> str:
        return (
            f"AdditiveSharingTensor(shape={self.shape}, "
            f"owners={self.owners}, encoder={self.encoder})"
        )


# --- syft-style fluent entry points ----------------------------------------


class FixedPrecisionTensor:
    """``fix_prec(x)`` wrapper so user code reads like the reference examples:
    ``fix_prec(x).share("alice", "bob", crypto_provider=cp)``."""

    def __init__(self, x: np.ndarray, base: int = 10, precision_fractional: int = 3):
        self.value = np.asarray(x)
        self.encoder = FixedPointEncoder(base, precision_fractional)

    def share(
        self,
        *owners: str,
        crypto_provider: CryptoProvider | None = None,
        key: jax.Array | None = None,
    ) -> AdditiveSharingTensor:
        return AdditiveSharingTensor.share(
            self.value, owners, crypto_provider, self.encoder, key
        )

    def float_prec(self) -> np.ndarray:
        return self.value


def fix_prec(
    x: np.ndarray, base: int = 10, precision_fractional: int = 3
) -> FixedPrecisionTensor:
    return FixedPrecisionTensor(x, base, precision_fractional)
