from pygrid_tpu.smpc.ring import (  # noqa: F401
    Ring64,
    from_ring,
    from_ring_signed,
    ring_add,
    ring_div_const,
    ring_div_const_signed,
    ring_matmul,
    ring_mul,
    ring_neg,
    ring_random,
    ring_sub,
    to_ring,
)
from pygrid_tpu.smpc.fixed import FixedPointEncoder  # noqa: F401
from pygrid_tpu.smpc.provider import CryptoProvider, CryptoStore  # noqa: F401
from pygrid_tpu.smpc.additive import (  # noqa: F401
    AdditiveSharingTensor,
    FixedPrecisionTensor,
    fix_prec,
)
from pygrid_tpu.smpc.remote import (  # noqa: F401
    RemoteCryptoProvider,
    RemoteSharedTensor,
    fix_prec_share_to_nodes,
    share_to_nodes,
)
from pygrid_tpu.smpc.encrypted_model import (  # noqa: F401
    EncryptedModel,
    SharedTensorRef,
    publish_encrypted_model,
    run_encrypted_oplist,
)
from pygrid_tpu.smpc.sharded import (  # noqa: F401
    make_sharded_beaver,
    make_sharded_open,
    sharded_beaver,
)
