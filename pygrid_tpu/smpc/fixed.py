"""Fixed-precision encoding into the 2^64 ring.

Parity surface: syft's ``FixedPrecisionTensor`` (``.fix_prec()`` /
``.float_prec()``) exercised by reference
``tests/data_centric/test_basic_syft_operations.py:383-453`` — base-10
encoding with ``precision_fractional=3`` by default, signed values living in
two's complement mod 2^64.
"""

from __future__ import annotations

import numpy as np

from pygrid_tpu.smpc.ring import (
    Ring64,
    from_ring_signed,
    ring_div_const_signed,
    to_ring,
)

DEFAULT_BASE = 10
DEFAULT_PRECISION = 3


class FixedPointEncoder:
    def __init__(
        self, base: int = DEFAULT_BASE, precision_fractional: int = DEFAULT_PRECISION
    ) -> None:
        if base ** precision_fractional >= (1 << 16):
            raise ValueError(
                "scale must stay < 2^16 so truncation's limb division is exact"
            )
        self.base = base
        self.precision_fractional = precision_fractional
        self.scale = base ** precision_fractional

    def encode(self, x: np.ndarray) -> Ring64:
        """float -> ring element round(x * scale) in two's complement."""
        v = np.round(np.asarray(x, dtype=np.float64) * self.scale).astype(np.int64)
        return to_ring(v.astype(np.uint64))

    def decode(self, r: Ring64) -> np.ndarray:
        """ring element -> float (host-side, exact int64 then divide)."""
        return from_ring_signed(r).astype(np.float64) / self.scale

    def truncate(self, r: Ring64) -> Ring64:
        """Rescale after a fixed-point multiply: signed divide by scale.

        On-device (jit-safe): used by the Beaver mul/matmul path where the
        product carries scale^2.
        """
        return ring_div_const_signed(r, self.scale)

    def __repr__(self) -> str:
        return (
            f"FixedPointEncoder(base={self.base}, "
            f"precision_fractional={self.precision_fractional})"
        )
