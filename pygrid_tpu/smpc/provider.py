"""Crypto provider — Beaver triple generation and the primitive store.

Parity surface: syft's ``crypto_provider`` worker and its crypto-store refill
protocol (``EmptyCryptoPrimitiveStoreError`` caught and serialized back at
reference ``events/data_centric/syft_events.py:34-38``; the provider is the
jth worker in ``x.share(alice, bob, crypto_provider=james)`` —
``test_basic_syft_operations.py:455-491``).

TPU-native: triples are generated *on device* (ring ops are jitted XLA) and
stored stacked over the party axis, so provisioning a batch of thousands of
simulated parties is one program launch.
"""

from __future__ import annotations

import jax

from pygrid_tpu.smpc import ring as R
from pygrid_tpu.smpc.kernels import reconstruct_kernel, share_kernel
from pygrid_tpu.utils.exceptions import EmptyCryptoPrimitiveStoreError


class CryptoStore:
    """FIFO store of precomputed triples keyed by (op, shapes, n_parties)."""

    def __init__(self) -> None:
        self._store: dict[tuple, list] = {}

    @staticmethod
    def key(op: str, shape_x: tuple, shape_y: tuple, n_parties: int) -> tuple:
        return (op, tuple(shape_x), tuple(shape_y), n_parties)

    def put(self, key: tuple, triple) -> None:
        self._store.setdefault(key, []).append(triple)

    def pop(self, key: tuple):
        bucket = self._store.get(key)
        if not bucket:
            raise EmptyCryptoPrimitiveStoreError(
                {
                    "op": key[0],
                    "shapes": [list(key[1]), list(key[2])],
                    "n_instances": 1,
                    "n_parties": key[3],
                }
            )
        return bucket.pop(0)

    def count(self, key: tuple) -> int:
        return len(self._store.get(key, []))


class CryptoProvider:
    """Trusted-dealer triple service running on the accelerator.

    ``strict_store=True`` reproduces the reference stack's refill behavior:
    requests only draw from the precomputed store and raise
    ``EmptyCryptoPrimitiveStoreError`` when dry (the caller then calls
    :meth:`provide` to refill — the round-trip the reference's error path
    serializes over the wire). Default mode generates on demand.
    """

    def __init__(
        self,
        id: str = "crypto_provider",
        seed: int | None = None,
        strict_store: bool = False,
        trusted_dealer: bool = False,
    ) -> None:
        self.id = id
        self.store = CryptoStore()
        self.strict_store = strict_store
        #: opt-in to the dealer-sees-all exact truncation
        #: (:meth:`reshare_truncated`); the default rescale path is the
        #: mask-and-open protocol built on :meth:`trunc_pair`, in which the
        #: dealer never reconstructs a secret
        self.trusted_dealer = trusted_dealer
        if seed is None:
            # triple secrecy rests on this randomness: a fixed default seed
            # would make every dealer's a/b stream publicly reproducible and
            # the Beaver open d = x - a would reveal x
            import secrets

            seed = secrets.randbits(63)
        self._seed = seed
        # lazy: creating a PRNGKey initializes the jax backend, and a node
        # server must not dial the accelerator just to exist — only the
        # first dealt primitive pays for backend init
        self._key: jax.Array | None = None

    def _next_key(self) -> jax.Array:
        if self._key is None:
            self._key = jax.random.PRNGKey(self._seed)
        self._key, sub = jax.random.split(self._key)
        return sub

    # --- triple generation --------------------------------------------------

    def _make_triple(
        self, op: str, shape_x: tuple, shape_y: tuple, n_parties: int
    ) -> tuple[R.Ring64, R.Ring64, R.Ring64]:
        ka, kb, ksa, ksb, ksc = jax.random.split(self._next_key(), 5)
        a = R.ring_random(ka, tuple(shape_x))
        b = R.ring_random(kb, tuple(shape_y))
        if op == "mul":
            c = R.ring_mul(a, b)
        elif op == "matmul":
            c = R.ring_matmul(a, b)
        else:
            raise ValueError(f"unknown triple op {op!r}")
        return (
            share_kernel(ksa, a, n_parties),
            share_kernel(ksb, b, n_parties),
            share_kernel(ksc, c, n_parties),
        )

    def _make_trunc_pair(
        self, shape: tuple, scale: int, n_parties: int
    ) -> tuple[R.Ring64, R.Ring64]:
        """A truncation pair: shares of ``r`` uniform in [0, 2^62) and of
        ``r' = floor(r / scale)`` — the preprocessed randomness for
        mask-and-open truncation (see :func:`pygrid_tpu.smpc.kernels.masked_truncate`).
        """
        import jax.numpy as jnp

        kr, ks1, ks2 = jax.random.split(self._next_key(), 3)
        r = R.ring_random(kr, tuple(shape))
        # clear the top 2 bits: r < 2^62 guarantees the masked open
        # z + OFFSET + r never wraps mod 2^64
        r = R.Ring64(r.lo, r.hi & jnp.uint32(0x3FFFFFFF))
        r_prime = R.ring_div_const(r, scale)
        return (
            share_kernel(ks1, r, n_parties),
            share_kernel(ks2, r_prime, n_parties),
        )

    def provide(
        self, op: str, shape_x: tuple, shape_y: tuple, n_parties: int,
        n_instances: int = 1,
    ) -> None:
        """Refill the store (the response to an empty-store error).

        ``op="trunc"`` refills truncation pairs: ``shape_x`` is the value
        shape and ``shape_y`` carries ``(scale,)``.
        """
        key = CryptoStore.key(op, shape_x, shape_y, n_parties)
        for _ in range(n_instances):
            if op == "trunc":
                item = self._make_trunc_pair(
                    tuple(shape_x), int(shape_y[0]), n_parties
                )
            else:
                item = self._make_triple(op, shape_x, shape_y, n_parties)
            self.store.put(key, item)

    def triple(
        self, op: str, shape_x: tuple, shape_y: tuple, n_parties: int
    ) -> tuple[R.Ring64, R.Ring64, R.Ring64]:
        key = CryptoStore.key(op, shape_x, shape_y, n_parties)
        if self.store.count(key):
            return self.store.pop(key)
        if self.strict_store:
            return self.store.pop(key)  # raises EmptyCryptoPrimitiveStoreError
        return self._make_triple(op, shape_x, shape_y, n_parties)

    def trunc_pair(
        self, shape: tuple, scale: int, n_parties: int
    ) -> tuple[R.Ring64, R.Ring64]:
        """Draw (or generate) one truncation pair for ``shape``/``scale``."""
        key = CryptoStore.key("trunc", tuple(shape), (int(scale),), n_parties)
        if self.store.count(key):
            return self.store.pop(key)
        if self.strict_store:
            return self.store.pop(key)  # raises EmptyCryptoPrimitiveStoreError
        return self._make_trunc_pair(tuple(shape), int(scale), n_parties)

    # --- provider-assisted exact truncation ---------------------------------

    def reshare_truncated(
        self, shares: R.Ring64, scale: int, n_parties: int
    ) -> R.Ring64:
        """Open → truncate exactly → re-share.

        Simulation-grade truncation (the dealer sees the value): exact and
        deterministic, which the protocol tests require. A deployment-grade
        replacement is probabilistic share-local truncation or a share
        conversion protocol; the call site is this one method.
        """
        truncated = R.ring_div_const_signed(reconstruct_kernel(shares), scale)
        return share_kernel(self._next_key(), truncated, n_parties)
