"""End-to-end encrypted inference across the grid — the reference's flagship
privacy flow (SURVEY §3.5), composed from the framework's own pieces:

1. **Publish** — a model owner fix-prec-shares each weight over share-holder
   nodes (one int64 share per node, a crypto-provider node for Beaver
   triples) and serves the inference Plan with ``mpc=True``; the served
   plan's State carries :class:`SharedTensorRef` wiring metadata (owners,
   share ids, encoder, provider) but **no share material**.
2. **Discover** — a data scientist asks the Network
   ``/search-encrypted-model`` (reference
   ``apps/network/src/app/routes/network.py:157-198``), which fans out to
   every node's ``/data-centric/search-encrypted-models`` (share-holder walk,
   reference ``routes/data_centric/routes.py:192-250``) and answers with the
   share-holders + crypto provider.
3. **Predict** — the client shares its input over the same holders, then
   runs the Plan's portable op-list where every value is a
   :class:`~pygrid_tpu.smpc.remote.RemoteSharedTensor`: linear ops are
   share-local pointer ops, every matmul/mul is a cross-node Beaver round
   dealt by the provider (reference inference entry
   ``events/data_centric/model_events.py:21-129``), and the prediction is
   reconstructed client-side — no single node ever holds the model weights,
   the input, or the output in the clear.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np

from pygrid_tpu.serde import register_serde
from pygrid_tpu.smpc.fixed import FixedPointEncoder
from pygrid_tpu.smpc.remote import (
    RemoteCryptoProvider,
    RemoteSharedTensor,
    fix_prec_share_to_nodes,
)
from pygrid_tpu.utils.exceptions import PyGridError


@register_serde(name="pygrid.SharedTensorRef")
class SharedTensorRef:
    """Where a state tensor's additive shares live: owners (node ids), the
    share object id at each owner, shape, fixed-point encoder params, and
    the crypto-provider id. This is what a served encrypted Plan's State
    carries across the wire — discovery metadata and wiring, zero secrets.
    Duck-typed like AdditiveSharingTensor (``owners``/``crypto_provider_id``)
    so the node's share-holder walk reports it."""

    def __init__(
        self,
        owners: Sequence[str],
        share_ids: Sequence[int],
        shape: Sequence[int],
        base: int,
        precision_fractional: int,
        crypto_provider_id: str | None,
    ) -> None:
        self.owners = tuple(owners)
        self.share_ids = tuple(share_ids)
        self.shape = tuple(int(s) for s in shape)
        self.base = base
        self.precision_fractional = precision_fractional
        self.crypto_provider_id = crypto_provider_id

    def _bufferize(self) -> dict:
        return {
            "owners": list(self.owners),
            "share_ids": list(self.share_ids),
            "shape": list(self.shape),
            "base": self.base,
            "precision_fractional": self.precision_fractional,
            "crypto_provider_id": self.crypto_provider_id,
        }

    @classmethod
    def _unbufferize(cls, data: dict) -> "SharedTensorRef":
        return cls(
            data["owners"],
            data["share_ids"],
            data["shape"],
            data["base"],
            data["precision_fractional"],
            data["crypto_provider_id"],
        )

    def __repr__(self) -> str:
        return (
            f"SharedTensorRef(owners={self.owners}, shape={self.shape}, "
            f"provider={self.crypto_provider_id!r})"
        )


def publish_encrypted_model(
    plan: Any,
    model_id: str,
    host_client: Any,
    holder_clients: Sequence[Any],
    provider_client: Any,
    weights: Sequence[np.ndarray],
    base: int = 10,
    precision_fractional: int = 3,
) -> list[RemoteSharedTensor]:
    """Share ``weights`` over the holder nodes and serve ``plan`` on the
    hosting node with ``mpc=True`` + ``allow_download=True`` (the plan blob
    a client downloads carries only the op-list and SharedTensorRefs).

    The provider node is dialed into every holder first so Beaver rounds
    can deal shares over the node mesh (reference
    ``connect_grid_nodes``, control_events.py:44-54)."""
    from pygrid_tpu.plans.state import State

    for holder in holder_clients:
        provider_client.connect_nodes(holder)
    provider = RemoteCryptoProvider(provider_client)

    shared: list[RemoteSharedTensor] = []
    refs: list[SharedTensorRef] = []
    for i, w in enumerate(weights):
        st = fix_prec_share_to_nodes(
            np.asarray(w),
            holder_clients,
            base=base,
            precision_fractional=precision_fractional,
            tags=(f"#emodel:{model_id}:state:{i}",),
            crypto_provider=provider,
        )
        shared.append(st)
        refs.append(
            SharedTensorRef(
                owners=[getattr(c, "id", "") for c in holder_clients],
                share_ids=[p.id_at_location for p in st.pointers],
                shape=np.shape(w),
                base=base,
                precision_fractional=precision_fractional,
                crypto_provider_id=provider.id,
            )
        )
    plan.state = State.from_tensors(refs)
    resp = host_client.serve_model(
        plan, model_id, mpc=True, allow_download=True
    )
    if not resp.get("success", True):
        raise PyGridError(str(resp))
    return shared


# --- the SMPC op-list interpreter -------------------------------------------
#
# Runs a Plan's portable op-list (plans/translators.py dialect) where values
# are RemoteSharedTensors. Linear structure ops are share-local; mul/matmul
# are cross-node Beaver rounds. The vocabulary covers SMPC-friendly
# inference graphs (affine layers + polynomial activations — the CryptoNets
# family); data-dependent nonlinearities (relu/max) need comparison
# protocols and are rejected explicitly rather than silently miscomputed.


def _shared_reshape(t: RemoteSharedTensor, shape: tuple) -> RemoteSharedTensor:
    ptrs = [p.remote_op("reshape", *shape) for p in t.pointers]
    return RemoteSharedTensor(ptrs, t.encoder, t.provider)


def _broadcast_in_dim(t, params) -> Any:
    """Materialize the broadcast share-locally (linear: broadcasting each
    additive share broadcasts the secret): insert size-1 axes per
    broadcast_dimensions, then remote ``broadcast_to`` the full shape."""
    shape = tuple(int(s) for s in params["shape"])
    bdims = tuple(int(d) for d in params["broadcast_dimensions"])
    in_shape = t.shape if isinstance(t, RemoteSharedTensor) else np.shape(t)
    aligned = [1] * len(shape)
    for in_ax, out_ax in enumerate(bdims):
        aligned[out_ax] = in_shape[in_ax]
    if isinstance(t, RemoteSharedTensor):
        aligned_t = _shared_reshape(t, tuple(aligned))
        ptrs = [
            p.remote_op("broadcast_to", shape=list(shape))
            for p in aligned_t.pointers
        ]
        return RemoteSharedTensor(ptrs, t.encoder, t.provider)
    return np.broadcast_to(np.reshape(t, aligned), shape)


def _dot_general(a, b, params):
    dnums = params["dimension_numbers"]
    contract = tuple(tuple(int(x) for x in d) for d in dnums[0])
    batch = tuple(tuple(int(x) for x in d) for d in dnums[1])
    plain_matmul = (
        contract == ((1,), (0,)) and batch == ((), ())
    )
    if not plain_matmul:
        raise PyGridError(
            f"encrypted dot_general supports plain 2D matmul only, got "
            f"dimension_numbers={dnums}"
        )
    if isinstance(a, RemoteSharedTensor) and isinstance(b, RemoteSharedTensor):
        return a @ b
    raise PyGridError(
        "encrypted matmul needs both operands shared — share the public side"
    )


def _add(a, b, params):
    if isinstance(a, RemoteSharedTensor) and isinstance(b, RemoteSharedTensor):
        return a + b
    if not isinstance(a, RemoteSharedTensor) and not isinstance(
        b, RemoteSharedTensor
    ):
        return np.add(a, b)
    raise PyGridError("encrypted add needs both operands shared")


def _mul(a, b, params):
    if isinstance(a, RemoteSharedTensor) and isinstance(b, RemoteSharedTensor):
        return a * b
    if not isinstance(a, RemoteSharedTensor) and not isinstance(
        b, RemoteSharedTensor
    ):
        return np.multiply(a, b)
    raise PyGridError("encrypted mul needs both operands shared")


def _sub(a, b, params):
    if isinstance(a, RemoteSharedTensor) and isinstance(b, RemoteSharedTensor):
        return a - b
    if not isinstance(a, RemoteSharedTensor) and not isinstance(
        b, RemoteSharedTensor
    ):
        return np.subtract(a, b)
    raise PyGridError("encrypted sub needs both operands shared")


_SMPC_OPS: dict[str, Callable] = {
    "dot_general": _dot_general,
    "add": _add,
    "add_any": _add,
    "sub": _sub,
    "mul": _mul,
    "broadcast_in_dim": lambda a, p: _broadcast_in_dim(a, p),
    "reshape": lambda a, p: _shared_reshape(
        a, tuple(int(s) for s in p["new_sizes"])
    )
    if isinstance(a, RemoteSharedTensor)
    else np.reshape(a, tuple(int(s) for s in p["new_sizes"])),
    "transpose": lambda a, p: RemoteSharedTensor(
        [q.remote_op("t") for q in a.pointers], a.encoder, a.provider
    )
    if isinstance(a, RemoteSharedTensor)
    else np.transpose(a, [int(x) for x in p["permutation"]]),
    # dtype bookkeeping from the float trace — shares are already ring
    # integers, nothing to convert
    "convert_element_type": lambda a, p: a,
}


def run_encrypted_oplist(oplist: dict, args: Sequence[Any]) -> Any:
    """Interpret a Plan op-list over RemoteSharedTensor/ndarray values."""
    env: dict[int, Any] = {}

    def read(ref):
        if "lit" in ref:
            return ref["lit"]
        if "lit_arr" in ref:
            return ref["lit_arr"]
        return env[ref["var"]]

    for cid, cval in zip(oplist["constvars"], oplist["consts"]):
        env[cid] = cval
    if len(args) != len(oplist["invars"]):
        raise PyGridError(
            f"plan expects {len(oplist['invars'])} inputs, got {len(args)}"
        )
    for iid, a in zip(oplist["invars"], args):
        env[iid] = a
    from pygrid_tpu.plans.translators import _CALL_OPS

    for eqn in oplist["eqns"]:
        invals = [read(r) for r in eqn["in"]]
        if eqn["op"] in _CALL_OPS:
            # jit/pjit wrapper: recurse into the inner jaxpr (same unwrap
            # as the plaintext interpreter, translators.py run_oplist)
            inner = None
            for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                cand = eqn["params"].get(key)
                if isinstance(cand, dict) and "__jaxpr__" in cand:
                    inner = cand["__jaxpr__"]
                    break
            if inner is None:
                raise PyGridError(f"no inner jaxpr for {eqn['op']!r}")
            out = run_encrypted_oplist(inner, invals)
            outs = out if isinstance(out, (list, tuple)) else [out]
        else:
            fn = _SMPC_OPS.get(eqn["op"])
            if fn is None:
                raise PyGridError(
                    f"op {eqn['op']!r} has no SMPC lowering (data-dependent "
                    "nonlinearities need comparison protocols; use polynomial "
                    "activations for encrypted inference)"
                )
            out = fn(*invals, eqn["params"])
            outs = out if isinstance(out, (list, tuple)) else [out]
        for oid, o in zip(eqn["out"], outs):
            env[oid] = o
    results = [read(r) for r in oplist["outvars"]]
    return results[0] if len(results) == 1 else results


# --- the data-scientist side -------------------------------------------------


class EncryptedModel:
    """Client handle to an encrypted model discovered through the Network."""

    def __init__(
        self,
        plan: Any,
        weights: list[RemoteSharedTensor],
        holder_clients: list[Any],
        provider: RemoteCryptoProvider,
        encoder: FixedPointEncoder,
        all_clients: list[Any] | None = None,
    ) -> None:
        self.plan = plan
        self.weights = weights
        self.holder_clients = holder_clients
        self.provider = provider
        self.encoder = encoder
        # every client discover() dialed (host included) — close() must
        # release them all, not just holders/provider
        self._all_clients = (
            list(all_clients)
            if all_clients is not None
            else holder_clients + [provider.location]
        )

    @classmethod
    def discover(
        cls,
        network_url: str,
        model_id: str,
        client_factory: Callable[[str], Any] | None = None,
        timeout: float = 30.0,
    ) -> "EncryptedModel":
        """Search the grid for ``model_id``'s share-holders, connect to
        them, download the plan from the hosting node, and wire up
        RemoteSharedTensor handles from its SharedTensorRefs."""
        import requests

        from pygrid_tpu.client.data_centric import DataCentricFLClient

        factory = client_factory or (
            lambda addr: DataCentricFLClient(addr, timeout=timeout)
        )
        resp = requests.post(
            network_url.rstrip("/") + "/search-encrypted-model",
            json={"model_id": model_id},
            timeout=timeout,
        )
        if resp.status_code != 200:
            raise PyGridError(
                f"encrypted-model search failed ({resp.status_code}): "
                f"{resp.text[:200]}"
            )
        match = resp.json().get("match-nodes") or {}
        if not match:
            raise PyGridError(f"no node hosts encrypted model {model_id!r}")
        host_id, info = next(iter(match.items()))
        worker_ids = info["nodes"]["workers"]
        provider_ids = info["nodes"]["crypto_provider"]
        if not provider_ids:
            raise PyGridError(
                f"model {model_id!r} has no crypto provider — its shares "
                "were placed without one, so Beaver rounds cannot be dealt"
            )
        addresses = dict(info.get("worker_addresses") or {})
        addresses.setdefault(host_id, info["address"])
        missing = [
            w for w in worker_ids + provider_ids if w not in addresses
        ]
        if missing:
            raise PyGridError(
                f"no grid address for share-holder(s) {missing}"
            )

        host = factory(info["address"])
        plan = host.download_model(model_id)
        refs = [
            t
            for t in (plan.state.tensors() if plan.state else [])
            if isinstance(t, SharedTensorRef)
        ]
        if not refs:
            raise PyGridError(f"model {model_id!r} carries no shared state")

        clients: dict[str, Any] = {host_id: host}

        def client_of(wid: str):
            if wid not in clients:
                clients[wid] = factory(addresses[wid])
            return clients[wid]

        provider_client = client_of(provider_ids[0])
        holder_clients = [client_of(w) for w in refs[0].owners]
        for holder in holder_clients:
            provider_client.connect_nodes(holder)
        provider = RemoteCryptoProvider(provider_client)
        encoder = FixedPointEncoder(
            refs[0].base, refs[0].precision_fractional
        )

        from pygrid_tpu.runtime.pointers import PointerTensor

        weights = [
            RemoteSharedTensor(
                [
                    PointerTensor(
                        location=client_of(o),
                        id_at_location=sid,
                        shape=ref.shape,
                    )
                    for o, sid in zip(ref.owners, ref.share_ids)
                ],
                FixedPointEncoder(ref.base, ref.precision_fractional),
                provider,
            )
            for ref in refs
        ]
        return cls(
            plan,
            weights,
            holder_clients,
            provider,
            encoder,
            all_clients=list(clients.values()),
        )

    def predict(self, x: np.ndarray) -> np.ndarray:
        """Share the input, run the op-list with cross-node Beaver rounds,
        reconstruct the prediction client-side."""
        sx = fix_prec_share_to_nodes(
            np.asarray(x, dtype=np.float32),
            self.holder_clients,
            base=self.encoder.base,
            precision_fractional=self.encoder.precision_fractional,
            crypto_provider=self.provider,
        )
        out = run_encrypted_oplist(
            self.plan.oplist["__jaxpr__"]
            if "__jaxpr__" in self.plan.oplist
            else self.plan.oplist,
            [sx] + list(self.weights),
        )
        if not isinstance(out, RemoteSharedTensor):
            raise PyGridError("encrypted plan did not produce a shared output")
        return out.get()

    def close(self) -> None:
        seen = set()
        for c in self._all_clients:
            if id(c) not in seen:
                seen.add(id(c))
                try:
                    c.close()
                except Exception:  # noqa: BLE001 — teardown
                    pass
