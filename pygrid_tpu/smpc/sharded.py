"""Mesh-sharded SMPC: the party axis as a ``jax.sharding.Mesh`` axis.

The TPU-native answer to the reference's share distribution across physical
nodes (``/root/reference/apps/network/src/app/routes/network.py:16,98-131``
hands each of 4 nodes one share): here parties are the leading array axis,
that axis is sharded over a mesh axis, each device holds its parties' shares
in its own HBM, and the only cross-party traffic in a Beaver round — opening
the masked values d = x−a and e = y−b — is a ``psum``-shaped collective over
the party axis riding ICI, not sockets (:func:`pygrid_tpu.smpc.ring.ring_psum`
does the exact mod-2^64 sum; carries can't ride a raw u32 psum).

Three tiers of the same kernels, one semantic:

- in-process protocol objects (``smpc.additive``) — parity surface;
- single-chip vmapped batches (``smpc.kernels``) — B×P virtual parties per
  launch;
- this module — parties (and/or instance batches) spread over a device mesh
  via ``shard_map``, scaling P beyond one chip's HBM.

Layout: stacked shares ``[P, B, ...]`` (party-major, then instance batch).
``in_specs=P(axis)`` shards the party axis; everything after it stays local.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pygrid_tpu.smpc import ring as R
from pygrid_tpu.smpc.kernels import share_kernel

from pygrid_tpu.parallel.compat import shard_map


def party_sharding(mesh: Mesh, axis: str = "parties") -> NamedSharding:
    """Sharding that puts the leading (party) axis on ``axis``."""
    return NamedSharding(mesh, P(axis))


def _batched(ring_op: Callable) -> Callable:
    """Lift a ring op over the instance-batch axis that follows the party
    axis (ring ops are written for single instances)."""
    return jax.vmap(ring_op)


def make_sharded_open(
    mesh: Mesh, axis: str = "parties"
) -> Callable[[R.Ring64], R.Ring64]:
    """Reconstruct ("open") shares ``[P, ...]`` sharded over ``axis``:
    one exact collective sum, result replicated on every device."""

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(),
        check_vma=False,
    )
    def open_(shares: R.Ring64) -> R.Ring64:
        return R.ring_psum(shares, axis, local_axis=0)

    return open_


def make_sharded_beaver(
    mesh: Mesh, op: str = "matmul", axis: str = "parties"
) -> Callable:
    """Beaver combine with the party axis sharded over ``axis``.

    Takes stacked shares ``x_sh, y_sh, a_sh, b_sh, c_sh`` of layout
    ``[P, B, ...]`` (triple shares from any dealer — ``share_kernel`` or the
    cross-node provider) and returns product shares, same layout. The two
    opens are party-axis collectives; everything else is local to each
    device's party block.
    """
    ring_op = R.ring_mul if op == "mul" else R.ring_matmul
    bop = _batched(ring_op)

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis),) * 5,
        out_specs=P(axis),
        check_vma=False,
    )
    def combine(x_sh, y_sh, a_sh, b_sh, c_sh):
        # local blocks: [P_local, B, ...]
        d = R.ring_psum(R.ring_sub(x_sh, a_sh), axis, local_axis=0)
        e = R.ring_psum(R.ring_sub(y_sh, b_sh), axis, local_axis=0)
        db = jax.vmap(lambda b: bop(d, b))(b_sh)
        ae = jax.vmap(lambda a: bop(a, e))(a_sh)
        z = R.ring_add(c_sh, R.ring_add(db, ae))
        # the public d∘e correction belongs to exactly one party: global
        # party 0 = local row 0 on the first shard of the axis
        de = bop(d, e)
        z0 = R.ring_add(R.Ring64(z.lo[0], z.hi[0]), de)
        is_first = (jax.lax.axis_index(axis) == 0).astype(jnp.uint32)
        head = R.Ring64(
            is_first * z0.lo + (1 - is_first) * z.lo[0],
            is_first * z0.hi + (1 - is_first) * z.hi[0],
        )
        return R.Ring64(
            z.lo.at[0].set(head.lo), z.hi.at[0].set(head.hi)
        )

    return combine


def deal_triples(
    key: jax.Array,
    x_shape: tuple,
    y_shape: tuple,
    n_parties: int,
    op: str = "matmul",
    batch: int | None = None,
) -> tuple[R.Ring64, R.Ring64, R.Ring64]:
    """Dealer-side triple generation for the sharded kernels: returns
    ``(a_sh, b_sh, c_sh)`` stacked ``[P, ...]`` (or ``[P, B, ...]``).
    Runs as ordinary jit — placed/partitioned by the caller's shardings;
    in production the cross-node provider (smpc/remote.py) plays dealer."""
    ring_op = R.ring_mul if op == "mul" else R.ring_matmul

    def one(k):
        k1, k2, k3 = jax.random.split(k, 3)
        a = R.ring_random(k1, x_shape)
        b = R.ring_random(k2, y_shape)
        c = ring_op(a, b)
        return (
            share_kernel(k3, a, n_parties),
            share_kernel(jax.random.fold_in(k3, 1), b, n_parties),
            share_kernel(jax.random.fold_in(k3, 2), c, n_parties),
        )

    if batch is None:
        return one(key)
    keys = jax.random.split(key, batch)
    a_sh, b_sh, c_sh = jax.vmap(one, out_axes=1)(keys)
    return a_sh, b_sh, c_sh


def sharded_beaver(
    mesh: Mesh,
    key: jax.Array,
    x_sh: R.Ring64,
    y_sh: R.Ring64,
    op: str = "matmul",
    axis: str = "parties",
) -> R.Ring64:
    """One full sharded Beaver round: deal triples, place shares on the
    party mesh axis, combine with collective opens."""
    n_parties = x_sh.lo.shape[0]
    batch = x_sh.lo.shape[1]
    a_sh, b_sh, c_sh = deal_triples(
        key,
        x_sh.lo.shape[2:],
        y_sh.lo.shape[2:],
        n_parties,
        op=op,
        batch=batch,
    )
    sharding = party_sharding(mesh, axis)
    place = lambda r: jax.tree.map(lambda a: jax.device_put(a, sharding), r)
    combine = make_sharded_beaver(mesh, op=op, axis=axis)
    return combine(
        place(x_sh), place(y_sh), place(a_sh), place(b_sh), place(c_sh)
    )
