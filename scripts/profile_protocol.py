"""Diagnostic: isolate the node-side per-report handler cost (no sockets).

Drives `route_requests` directly with authenticate → cycle-request →
report messages for W workers × R cycles, timing each phase — the
load-independent twin of bench.py's protocol bench. Run:

    python scripts/profile_protocol.py [--wire json|binary] [--profile]
"""

from __future__ import annotations

import argparse
import base64
import cProfile
import io
import json
import pstats
import sys
import time

import numpy as np

sys.path.insert(0, ".")

W, R = 16, 3
SIZES = (784, 392, 10)
BATCH = 64


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--wire", default="json", choices=["json", "binary"])
    ap.add_argument("--profile", action="store_true")
    args = ap.parse_args()
    bf16 = args.wire == "binary"

    import jax

    jax.config.update("jax_platforms", "cpu")

    from pygrid_tpu.federated import tasks
    from pygrid_tpu.models import mlp
    from pygrid_tpu.node import NodeContext
    from pygrid_tpu.node.events import Connection, route_requests
    from pygrid_tpu.plans.plan import Plan
    from pygrid_tpu.plans.state import serialize_model_params
    from pygrid_tpu.serde import deserialize, serialize

    tasks.set_sync(True)
    ctx = NodeContext("profile-node")
    params = [np.asarray(p) for p in mlp.init(jax.random.PRNGKey(0), SIZES)]
    plan = Plan(name="training_plan", fn=mlp.training_step)
    plan.build(
        np.zeros((BATCH, SIZES[0]), np.float32),
        np.zeros((BATCH, SIZES[-1]), np.float32),
        np.float32(0.1),
        *params,
    )
    from pygrid_tpu.serde import to_hex

    ctx.fl.create_process(
        model_blob=serialize_model_params(params),
        client_plans={"training_plan": bytes.fromhex(to_hex(plan))},
        name="prof", version="1.0",
        client_config={"name": "prof", "version": "1.0"},
        server_config={
            "min_workers": W, "max_workers": W,
            "min_diffs": W, "max_diffs": W, "num_cycles": R + 1,
            "do_not_reuse_workers_until_cycle": 0,
            "pool_selection": "random",
        },
        server_averaging_plan=None,
        client_protocols={},
    )

    diff = [0.01 * p for p in params]
    blob = serialize_model_params(diff, bf16=bf16)

    def send_json(conn, msg_type, data):
        out = route_requests(
            ctx, json.dumps({"type": msg_type, "data": data}), conn
        )
        return json.loads(out)["data"]

    def send_bin(conn, msg_type, data):
        out = route_requests(
            ctx, serialize({"type": msg_type, "data": data}), conn
        )
        return deserialize(out)["data"]

    send = send_bin if bf16 else send_json

    phase_t: dict[str, list[float]] = {}

    def timed(name, fn, *a):
        t0 = time.perf_counter()
        out = fn(*a)
        phase_t.setdefault(name, []).append(time.perf_counter() - t0)
        return out

    conns = [Connection(ctx, socket=object()) for _ in range(W)]
    wids = []
    for conn in conns:
        out = timed(
            "auth", send, conn, "model-centric/authenticate",
            {"model_name": "prof", "model_version": "1.0"},
        )
        wids.append(out["worker_id"])

    profiler = cProfile.Profile() if args.profile else None

    t_all0 = time.perf_counter()
    for _ in range(R):
        keys = []
        for conn, wid in zip(conns, wids):
            out = timed(
                "cycle_request", send, conn, "model-centric/cycle-request",
                {"worker_id": wid, "model": "prof", "version": "1.0",
                 "ping": 1.0, "download": 1000.0, "upload": 1000.0},
            )
            assert out.get("status") == "accepted", out
            keys.append(out["request_key"])
        if profiler:
            profiler.enable()
        for conn, wid, key in zip(conns, wids, keys):
            payload = (
                blob if bf16 else base64.b64encode(blob).decode()
            )
            out = timed(
                "report", send, conn, "model-centric/report",
                {"worker_id": wid, "request_key": key, "diff": payload},
            )
            assert out.get("status") == "success", out
        if profiler:
            profiler.disable()
    wall = time.perf_counter() - t_all0

    for name, ts in phase_t.items():
        arr = np.asarray(ts) * 1e3
        print(
            f"{name:14s} n={len(arr):3d}  mean={arr.mean():7.2f} ms  "
            f"p50={np.percentile(arr, 50):7.2f}  max={arr.max():7.2f}",
            file=sys.stderr,
        )
    n_reports = W * R
    print(
        f"wall {wall:.2f}s for {n_reports} reports "
        f"({n_reports / wall:.1f} reports/sec incl. cycle completion)",
        file=sys.stderr,
    )
    if profiler:
        s = io.StringIO()
        pstats.Stats(profiler, stream=s).sort_stats("cumulative").print_stats(30)
        print(s.getvalue(), file=sys.stderr)


if __name__ == "__main__":
    main()
