#!/usr/bin/env sh
# Serving-path smoke: tiny transformer, CPU only, no sockets — catches
# continuous-batching throughput and recompile regressions in seconds,
# without a TPU or a live node. The same assertions run under tier-1 via
# tests/unit/test_bench_serving.py; the full-size capture is bench.py's
# bench_serving() section (recorded into the round's BENCH file).
#
# Usage: scripts/bench_serving.sh [--full]
set -e
cd "$(dirname "$0")/.."
TINY=True
[ "$1" = "--full" ] && TINY=False
JAX_PLATFORMS=cpu python -c "
import json
from bench import bench_serving
print(json.dumps(bench_serving(tiny=$TINY), indent=2))
"
