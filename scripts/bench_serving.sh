#!/usr/bin/env sh
# Serving-path smoke: tiny transformer, CPU only, no sockets — catches
# continuous-batching throughput, paged-KV capacity, prefix-cache,
# fused-decode steady-state and recompile regressions in seconds,
# without a TPU or a live node. The same assertions run under tier-1
# via tests/unit/test_bench_serving.py; the full-size captures are
# bench.py's bench_serving(), bench_serving_paged() and
# bench_serving_fused() sections (recorded into the round's BENCH
# file — the fused section also reports the speculative path's
# acceptance rate and net ratio, honestly).
#
# Usage: scripts/bench_serving.sh [--full]
set -e
cd "$(dirname "$0")/.."
TINY=True
[ "$1" = "--full" ] && TINY=False
JAX_PLATFORMS=cpu python -c "
import json
from bench import bench_serving, bench_serving_paged, bench_serving_fused
out = bench_serving(tiny=$TINY)
out.update(bench_serving_paged(tiny=$TINY))
out.update(bench_serving_fused(tiny=$TINY))
print(json.dumps(out, indent=2))
"
