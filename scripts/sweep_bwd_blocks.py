"""On-chip sweep of the flash backward block sizes (BWD_BLOCK_Q/K).

Times the full grad step (fwd kernel + both bwd kernels) via scan-chain
marginals with value fetch (the tunnel defers execution until a fetch).
"""
from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp

from pygrid_tpu.parallel import pallas_attention as pa

B, L, H, D = 4, 4096, 8, 128


def make_chain(n, bq=None, bk=None):
    kw = {}
    if bq is not None:
        kw = {"bwd_block_q": bq, "bwd_block_k": bk}

    def loss(q, k, v):
        return jnp.sum(
            pa.flash_attention(q, k, v, causal=True, **kw).astype(
                jnp.float32
            )
        )

    g = jax.grad(loss, argnums=(0, 1, 2))

    @jax.jit
    def chain(q, k, v):
        def body(carry, _):
            qq, kk, vv = carry
            dq, dk, dv = g(qq, kk, vv)
            return (qq + dq * 1e-6, kk + dk * 1e-6, vv + dv * 1e-6), dq[0, 0, 0, 0]

        _, outs = jax.lax.scan(body, (q, k, v), None, length=n)
        return outs[-1]

    return chain


def marginal(q, k, v, bq=None, bk=None, small=2, large=8, reps=5):
    fns = {n: make_chain(n, bq, bk) for n in (small, large)}
    for f in fns.values():
        _ = float(f(q, k, v))

    def run(n):
        t0 = time.perf_counter()
        _ = float(fns[n](q, k, v))
        return time.perf_counter() - t0

    ts = min(run(small) for _ in range(reps))
    tl = min(run(large) for _ in range(reps))
    return (tl - ts) / (large - small)


def main():
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, L, H, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, L, H, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, L, H, D), jnp.bfloat16)
    dots = 2 * L * L * D * B * H * 0.5
    for bq in (256, 512, 1024):
        for bk in (256, 512, 1024):
            try:
                t = marginal(q, k, v, bq, bk)
            except Exception as e:
                print(f"bq={bq:5d} bk={bk:5d}: FAIL {type(e).__name__}",
                      file=sys.stderr)
                continue
            eff = 9 * dots / t / 197e12 * 100
            print(
                f"bq={bq:5d} bk={bk:5d}: {t*1e3:7.2f} ms  eff(9dot) {eff:5.1f}%",
                file=sys.stderr,
            )


if __name__ == "__main__":
    main()
