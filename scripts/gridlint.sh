#!/usr/bin/env bash
# gridlint — the repo-native static-analysis gate (docs/ANALYSIS.md).
# Runs the full suite over pygrid_tpu/ against the committed baseline;
# exits non-zero on any non-baselined finding. Tier-1 runs the same
# suite in-process via tests/unit/test_gridlint_clean.py.
#
#   scripts/gridlint.sh                # full tree, strict baseline
#   scripts/gridlint.sh --changed      # git-changed files + their
#                                      # call-graph dependents (the
#                                      # fast pre-commit loop)
#   scripts/gridlint.sh --sarif [out]  # SARIF 2.1.0 report (witness
#                                      # chains as codeFlows); under
#                                      # GITHUB_ACTIONS the artifact
#                                      # name is auto-selected
#   scripts/gridlint.sh --explain GL205  # witness chains for one rule
#
# Under GitHub Actions the findings are emitted as ::warning
# annotations (one per finding) so CI surfaces them inline on the PR —
# pass an explicit --format to override.
set -euo pipefail
cd "$(dirname "$0")/.."

# --sarif [path]: emit SARIF; auto-name the artifact in CI so upload
# steps can glob gridlint-*.sarif without coordination
if [ "${1:-}" = "--sarif" ]; then
  shift
  out=""
  if [ $# -gt 0 ] && [ "${1#-}" = "$1" ]; then
    out="$1"; shift
  elif [ "${GITHUB_ACTIONS:-}" = "true" ]; then
    out="gridlint-${GITHUB_RUN_ID:-local}.sarif"
  fi
  if [ -n "$out" ]; then
    exec python -m pygrid_tpu.analysis --strict-baseline \
      --format sarif --output "$out" "$@"
  fi
  exec python -m pygrid_tpu.analysis --strict-baseline --format sarif "$@"
fi

if [ "${GITHUB_ACTIONS:-}" = "true" ]; then
  case " $* " in
    *" --format"*|*" --explain"*) ;;
    *) set -- --format github "$@" ;;
  esac
fi
exec python -m pygrid_tpu.analysis --strict-baseline "$@"
