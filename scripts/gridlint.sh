#!/usr/bin/env bash
# gridlint — the repo-native static-analysis gate (docs/ANALYSIS.md).
# Runs the full suite over pygrid_tpu/ against the committed baseline;
# exits non-zero on any non-baselined finding. Tier-1 runs the same
# suite in-process via tests/unit/test_gridlint_clean.py.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m pygrid_tpu.analysis --strict-baseline "$@"
