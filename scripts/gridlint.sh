#!/usr/bin/env bash
# gridlint — the repo-native static-analysis gate (docs/ANALYSIS.md).
# Runs the full suite over pygrid_tpu/ against the committed baseline;
# exits non-zero on any non-baselined finding. Tier-1 runs the same
# suite in-process via tests/unit/test_gridlint_clean.py.
#
#   scripts/gridlint.sh                # full tree, strict baseline
#   scripts/gridlint.sh --changed      # git-changed files + their
#                                      # call-graph dependents (the
#                                      # fast pre-commit loop)
#
# Under GitHub Actions the findings are emitted as ::warning
# annotations (one per finding) so CI surfaces them inline on the PR —
# pass an explicit --format to override.
set -euo pipefail
cd "$(dirname "$0")/.."
if [ "${GITHUB_ACTIONS:-}" = "true" ]; then
  case " $* " in
    *" --format"*) ;;
    *) set -- --format github "$@" ;;
  esac
fi
exec python -m pygrid_tpu.analysis --strict-baseline "$@"
