#!/usr/bin/env bash
# gridstorm — open-loop load + fault-injection storms (docs/STORM.md).
# Drives a real in-process node+network+subagg topology on the CPU
# twin, injects the scenario's fault schedule, and exits non-zero if
# any reaction assertion fails. Tier-1 runs the smoke scenario
# in-process via tests/integration/test_storm_smoke.py.
#
#   scripts/gridstorm.sh                  # the full acceptance storm
#   scripts/gridstorm.sh --smoke          # tier-1 smoke storm (≤30 s)
#   scripts/gridstorm.sh --scenario NAME  # any built-in (--list)
#   scripts/gridstorm.sh --spec file.yaml # declarative scenario spec
#   scripts/gridstorm.sh --replay DUMP    # replay a flight dump as a
#                                         # regression scenario
set -euo pipefail
cd "$(dirname "$0")/.."

# storms are CPU-twin affairs: pin the platform so an attached
# accelerator never changes the breach math a scenario was tuned for
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

if [ "${1:-}" = "--smoke" ]; then
  shift
  exec python -m pygrid_tpu.storm --scenario smoke "$@"
fi
if [ $# -gt 0 ]; then
  exec python -m pygrid_tpu.storm "$@"
fi
exec python -m pygrid_tpu.storm --scenario full
