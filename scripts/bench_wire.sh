#!/usr/bin/env sh
# Wire-path smoke: tiny checkpoint sizes, CPU only, no sockets — catches
# encode/decode and bytes-ratio regressions in seconds, without a TPU or a
# live node. The same assertions run under tier-1 via
# tests/unit/test_bench_wire.py; the full-size capture is bench.py's
# bench_wire() section (recorded into the round's BENCH file).
#
# Usage: scripts/bench_wire.sh [--full]
set -e
cd "$(dirname "$0")/.."
TINY=True
[ "$1" = "--full" ] && TINY=False
JAX_PLATFORMS=cpu python -c "
import json
from bench import bench_telemetry_overhead, bench_wire
out = bench_wire(tiny=$TINY)
out.update(bench_telemetry_overhead(tiny=$TINY))
print(json.dumps(out, indent=2))
"
