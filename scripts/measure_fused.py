"""On-chip comparison of the FedAvg round builders (scratch measurement).

Usage: python scripts/measure_fused.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp

from bench import BATCH, K, LR, PEAK_TFLOPS, SIZES, _flops_per_round
from pygrid_tpu.models import mlp
from pygrid_tpu.parallel import make_fused_rounds, make_scanned_rounds


def flops_per_round(local_steps=1):
    return _flops_per_round() * local_steps


def measure(fn, params, X, y, lr, n_small, n_large, trials=6):
    def run(f):
        t0 = time.perf_counter()
        out = f(params, X, y, lr)
        _ = float(out[1][-1])
        return time.perf_counter() - t0

    t_s = min(run(fn[n_small]) for _ in range(trials))
    t_l = min(run(fn[n_large]) for _ in range(trials))
    return (t_l - t_s) / (n_large - n_small)


def main():
    print(f"device: {jax.devices()[0]}", file=sys.stderr)
    params = mlp.init(jax.random.PRNGKey(0), SIZES)
    X = jax.random.normal(jax.random.PRNGKey(1), (K, BATCH, SIZES[0]))
    labels = jax.random.randint(jax.random.PRNGKey(2), (K, BATCH), 0, SIZES[-1])
    y = jax.nn.one_hot(labels, SIZES[-1])
    lr = jnp.float32(LR)
    n_s, n_l = 10, 200

    cases = {
        "opaque N=1": lambda n: make_scanned_rounds(
            mlp.training_step, n, local_steps=1,
            matmul_precision="BF16_BF16_F32"),
        "fused  N=1": lambda n: make_fused_rounds(
            mlp.loss_and_acc, n, local_steps=1,
            matmul_precision="BF16_BF16_F32"),
        "folded N=1": lambda n: make_scanned_rounds(
            mlp.training_step, n, local_steps=1,
            matmul_precision="BF16_BF16_F32", fold_clients=True),
        "opaque N=4": lambda n: make_scanned_rounds(
            mlp.training_step, n, local_steps=4,
            matmul_precision="BF16_BF16_F32"),
        "fused  N=4": lambda n: make_fused_rounds(
            mlp.loss_and_acc, n, local_steps=4,
            matmul_precision="BF16_BF16_F32"),
        "fusedb N=4": lambda n: make_fused_rounds(
            mlp.loss_and_acc, n, local_steps=4,
            matmul_precision="BF16_BF16_F32", carry_dtype=jnp.bfloat16),
    }
    for name, mk in cases.items():
        steps = 4 if "N=4" in name else 1
        fns = {n: mk(n) for n in (n_s, n_l)}
        for f in fns.values():
            out = f(params, X, y, lr)
            _ = float(out[1][-1])
        dt = measure(fns, params, X, y, lr, n_s, n_l)
        mfu = flops_per_round(steps) / dt / (PEAK_TFLOPS * 1e12)
        print(
            f"{name}: {dt*1e3:.3f} ms/round  MFU {mfu*100:.1f}%",
            file=sys.stderr,
        )


if __name__ == "__main__":
    main()
