"""Decompose the flagship fed-transformer round time on the real chip.

Times the full training step against ablations (identity attention, XLA
attention, fwd-only, small vocab) with the round-4 marginal-timing recipe:
scan chains of 2 vs 10 rounds, min-of-5, slope = per-round time — immune
to the tunnel's 20-70 ms per-call jitter.
"""
from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp

from pygrid_tpu.models import transformer
from pygrid_tpu.parallel import make_scanned_rounds
from pygrid_tpu.parallel.pallas_attention import flash_attention


def time_marginal(fn, args, small=2, large=10, reps=5):
    fns = {}
    for n in (small, large):
        fns[n] = make_scanned_rounds(fn, n_rounds=n)
        out = fns[n](*args)
        _ = float(out[1][-1])

    def run(n):
        t0 = time.perf_counter()
        out = fns[n](*args)
        _ = float(out[1][-1])
        return time.perf_counter() - t0

    t_s = min(run(small) for _ in range(reps))
    t_l = min(run(large) for _ in range(reps))
    return (t_l - t_s) / (large - small)


def main():
    cfg = transformer.TransformerConfig(
        vocab=8192, d_model=512, n_heads=8, n_layers=4, d_ff=2048,
        max_len=512,
    )
    Kc, Bc, L = 8, 4, 512
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    X = jax.random.randint(jax.random.PRNGKey(1), (Kc, Bc, L), 0, cfg.vocab)
    y = jnp.roll(X, -1, axis=-1)
    lr = jnp.float32(0.1)
    args = (params, X, y, lr)

    def ident_attn(q, k, v, causal=True):
        return v

    def xla_attn(q, k, v, causal=True):
        from pygrid_tpu.parallel.ring_attention import attention
        return attention(q, k, v, causal=causal)

    variants = {
        "flash (flagship)": transformer.make_training_step(
            cfg, attn_fn=flash_attention, compute_dtype="bfloat16"),
        "xla attention": transformer.make_training_step(
            cfg, attn_fn=xla_attn, compute_dtype="bfloat16"),
        "identity attention": transformer.make_training_step(
            cfg, attn_fn=ident_attn, compute_dtype="bfloat16"),
    }
    for name, step in variants.items():
        per = time_marginal(step, args)
        print(f"{name:24s}: {per*1e3:8.2f} ms/round", file=sys.stderr)

    # small vocab isolates the logits/log_softmax plane
    cfg_sv = cfg._replace(vocab=512)
    params_sv = transformer.init(jax.random.PRNGKey(0), cfg_sv)
    X_sv = jnp.clip(X, 0, 511)
    y_sv = jnp.roll(X_sv, -1, axis=-1)
    step_sv = transformer.make_training_step(
        cfg_sv, attn_fn=flash_attention, compute_dtype="bfloat16")
    per = time_marginal(step_sv, (params_sv, X_sv, y_sv, lr))
    print(f"{'flash vocab=512':24s}: {per*1e3:8.2f} ms/round", file=sys.stderr)

    # 4 heads => head_dim 128, no pad waste in the kernel
    cfg_h4 = cfg._replace(n_heads=4)
    step_h4 = transformer.make_training_step(
        cfg_h4, attn_fn=flash_attention, compute_dtype="bfloat16")
    per = time_marginal(step_h4, args)
    print(f"{'flash heads=4 (dh=128)':24s}: {per*1e3:8.2f} ms/round", file=sys.stderr)

    # fwd-only (loss, no grad): how much is backward?
    def fwd_only(X, y, lr, *params):
        loss, acc = transformer.loss_and_acc(
            list(params), X, y, cfg, flash_attention,
            compute_dtype="bfloat16")
        return (loss, acc, *params)

    per = time_marginal(fwd_only, args)
    print(f"{'fwd-only flash':24s}: {per*1e3:8.2f} ms/round", file=sys.stderr)


if __name__ == "__main__":
    main()
