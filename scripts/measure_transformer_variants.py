"""On-chip comparison of fed-transformer round builders (scratch).

Usage: python scripts/measure_transformer_variants.py [flagship|long]
"""

import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp

from bench import PEAK_TFLOPS
from pygrid_tpu.models import transformer
from pygrid_tpu.parallel import make_fused_rounds, make_scanned_rounds
from pygrid_tpu.parallel.pallas_attention import flash_attention


def flops_round(cfg, Kc, Bc):
    L = cfg.max_len
    tokens = Kc * Bc * L
    n_matmul = cfg.n_layers * (
        4 * cfg.d_model**2 + 2 * cfg.d_model * cfg.d_ff
    ) + cfg.vocab * cfg.d_model
    return (
        6.0 * n_matmul * tokens
        + 12.0 * cfg.n_layers * L * cfg.d_model * tokens
    ), tokens


def measure(mk, params, X, y, lr, small, large, trials=5):
    fns = {n: mk(n) for n in (small, large)}
    for fn in fns.values():
        out = fn(params, X, y, lr)
        _ = float(out[1][-1])

    def run(n):
        t0 = time.perf_counter()
        out = fns[n](params, X, y, lr)
        _ = float(out[1][-1])
        return time.perf_counter() - t0

    t_s = min(run(small) for _ in range(trials))
    t_l = min(run(large) for _ in range(trials))
    return (t_l - t_s) / (large - small)


def report(name, per, fl, tokens):
    mfu = fl / per / (PEAK_TFLOPS * 1e12)
    print(
        f"{name}: {per*1e3:.2f} ms/round, {tokens/per:,.0f} tok/s, "
        f"MFU {mfu*100:.1f}%",
        file=sys.stderr,
    )


def flagship():
    cfg = transformer.TransformerConfig(
        vocab=8192, d_model=512, n_heads=4, n_layers=4, d_ff=2048,
        max_len=512,
    )
    Kc, Bc = 8, 4
    fl, tokens = flops_round(cfg, Kc, Bc)
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    X = jax.random.randint(jax.random.PRNGKey(1), (Kc, Bc, cfg.max_len), 0, cfg.vocab)
    y = jnp.roll(X, -1, axis=-1)
    lr = jnp.float32(0.1)

    step = transformer.make_training_step(
        cfg, attn_fn=flash_attention, compute_dtype="bfloat16"
    )
    loss_fn = partial(
        transformer.loss_and_acc, cfg=cfg, attn_fn=flash_attention,
        compute_dtype="bfloat16",
    )
    per = measure(
        lambda n: make_scanned_rounds(step, n_rounds=n),
        params, X, y, lr, 2, 10,
    )
    report("opaque", per, fl, tokens)
    per = measure(
        lambda n: make_fused_rounds(loss_fn, n_rounds=n),
        params, X, y, lr, 2, 10,
    )
    report("fused ", per, fl, tokens)
    step_g = transformer.make_training_step(
        cfg, attn_fn=flash_attention, compute_dtype="bfloat16",
        ce_grad_dtype="bfloat16",
    )
    per = measure(
        lambda n: make_scanned_rounds(step_g, n_rounds=n),
        params, X, y, lr, 2, 10,
    )
    report("opaque ce_bf16bwd", per, fl, tokens)
    loss_fn_g = partial(
        transformer.loss_and_acc, cfg=cfg, attn_fn=flash_attention,
        compute_dtype="bfloat16", ce_grad_dtype="bfloat16",
    )
    per = measure(
        lambda n: make_fused_rounds(loss_fn_g, n_rounds=n),
        params, X, y, lr, 2, 10,
    )
    report("fused  ce_bf16bwd", per, fl, tokens)


def long_ctx():
    for L, Kc in ((4096, 8), (8192, 4)):
        cfg = transformer.TransformerConfig(
            vocab=8192, d_model=512, n_heads=4, n_layers=4, d_ff=2048,
            max_len=L,
        )
        fl, tokens = flops_round(cfg, Kc, 1)
        params = transformer.init(jax.random.PRNGKey(0), cfg)
        X = jax.random.randint(jax.random.PRNGKey(1), (Kc, 1, L), 0, cfg.vocab)
        y = jnp.roll(X, -1, axis=-1)
        lr = jnp.float32(0.1)
        variants = {
            "remat=True ": dict(remat=True),
            "remat=True  ce_bf16": dict(remat=True, ce_grad_dtype="bfloat16"),
            "remat=dots  ce_bf16": dict(remat="dots", ce_grad_dtype="bfloat16"),
            "remat=False ce_bf16": dict(remat=False, ce_grad_dtype="bfloat16"),
        }
        for name, kw in variants.items():
            loss_fn = partial(
                transformer.loss_and_acc, cfg=cfg, attn_fn=flash_attention,
                compute_dtype="bfloat16", **kw,
            )
            try:
                per = measure(
                    lambda n: make_fused_rounds(loss_fn, n_rounds=n),
                    params, X, y, lr, 1, 4, trials=4,
                )
                report(f"L={L} fused {name}", per, fl, tokens)
            except Exception as e:
                print(f"L={L} fused {name}: FAILED {type(e).__name__}: "
                      f"{str(e)[:200]}", file=sys.stderr)
        # opaque remat=True reference (current bench path)
        step = transformer.make_training_step(
            cfg, attn_fn=flash_attention, compute_dtype="bfloat16",
            remat=True,
        )
        per = measure(
            lambda n: make_scanned_rounds(step, n_rounds=n),
            params, X, y, lr, 1, 4, trials=4,
        )
        report(f"L={L} opaque remat=True ", per, fl, tokens)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "flagship"
    print(f"device: {jax.devices()[0]}", file=sys.stderr)
    if which == "flagship":
        flagship()
    else:
        long_ctx()
