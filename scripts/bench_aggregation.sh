#!/usr/bin/env sh
# Hierarchical-aggregation bench: W simulated workers fold through
# sub-aggregator partials into one live node over real wire-v2 sockets,
# vs the flat per-worker leaf path — with a fanout sweep, peak-RSS
# tracking, and a single-connection tracemalloc pass that shows node
# allocation peaks flat from 64 to 1k workers (docs/AGGREGATION.md).
# The smoke-scale assertions run under tier-1 via
# tests/unit/test_bench_aggregation.py; the full capture lands in the
# round's BENCH file via bench.py's protocol_hier section.
#
# Usage: scripts/bench_aggregation.sh [--smoke]
#   default: 64/1k/10k workers, fanouts 64 and 256 (~5 min, CPU only)
#   --smoke: 64/256 workers, fanout 32 (~30 s)
set -e
cd "$(dirname "$0")/.."
if [ "$1" = "--smoke" ]; then
    export PYGRID_BENCH_HIER_WORKERS=64,256
    export PYGRID_BENCH_HIER_FANOUTS=32
    export PYGRID_BENCH_HIER_FLAT=64
fi
JAX_PLATFORMS=cpu python -c "
import json
from bench import bench_protocol_hier
print(json.dumps(bench_protocol_hier(), indent=2))
"
