#!/bin/bash
set -e
python3 -m pip install pygrid-tpu
export DATABASE_URL=grid.db
exec python3 -m pygrid_tpu.node --id alice --host 0.0.0.0 --port 5000 --network http://network.example.com:7000
