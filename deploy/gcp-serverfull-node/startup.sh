#!/bin/bash
set -e
python -m pip install pygrid-tpu
export DATABASE_URL=grid.db
exec python -m pygrid_tpu.node --id alice --host 0.0.0.0 --port 5000 --network http://network.example.com:7000
