#!/bin/bash
set -e
python -m pip install pygrid-tpu
export DATABASE_URL=grid.db
exec python -m pygrid_tpu.network --host 0.0.0.0 --port 7000
