"""Regenerate the checked-in exemplar Terraform stacks.

The reference ships hand-written HCL per stack
(``/root/reference/deploy/serverless-node/*.tf``,
``deploy/serverless-network/*.tf``, ``deploy/serverfull-node/main.tf``);
here each stack is RENDERED by the same provider builders the deploy API
uses (``pygrid_tpu.infra.providers.gcp``), so the checked-in configs can
never drift from what ``pygrid-tpu deploy`` writes. A unit test
(tests/unit/test_infra.py) asserts the rendered output matches these files.

Run from the repo root:  python deploy/regenerate.py
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from pygrid_tpu.infra.config import DeployConfig  # noqa: E402
from pygrid_tpu.infra.providers import build_provider  # noqa: E402

HERE = pathlib.Path(__file__).resolve().parent

#: the exemplar stacks — the reference's three deploy/ directories plus the
#: serverless-network twin it also ships
STACKS: dict[str, dict] = {
    "gcp-serverfull-node": {
        "provider": "gcp",
        "deployment_type": "serverfull",
        "app": {"name": "node", "id": "alice", "port": 5000,
                "network": "http://network.example.com:7000"},
    },
    "gcp-serverfull-network": {
        "provider": "gcp",
        "deployment_type": "serverfull",
        "app": {"name": "network", "port": 7000},
    },
    "gcp-serverless-node": {
        "provider": "gcp",
        "deployment_type": "serverless",
        "app": {"name": "node", "id": "alice", "port": 5000},
    },
    "gcp-serverless-network": {
        "provider": "gcp",
        "deployment_type": "serverless",
        "app": {"name": "network", "port": 7000},
    },
    # the reference's own concrete cloud target (its deploy/serverless-node
    # stack) — coordination plane on AWS; TPU compute stays on GCP
    "aws-serverless-node": {
        "provider": "aws",
        "deployment_type": "serverless",
        "app": {"name": "node", "id": "alice", "port": 5000},
    },
    "aws-serverfull-node": {
        "provider": "aws",
        "deployment_type": "serverfull",
        "app": {"name": "node", "id": "alice", "port": 5000,
                "network": "http://network.example.com:7000"},
    },
    # the reference's CLI listed azure but only ever shipped a stub class
    # (api/providers/azure/azure.py:1-10) — these are working twins
    "azure-serverfull-node": {
        "provider": "azure",
        "deployment_type": "serverfull",
        "app": {"name": "node", "id": "alice", "port": 5000,
                "network": "http://network.example.com:7000"},
    },
    "azure-serverless-node": {
        "provider": "azure",
        "deployment_type": "serverless",
        "app": {"name": "node", "id": "alice", "port": 5000},
    },
}


def render_stack(name: str) -> dict[str, str]:
    spec = dict(STACKS[name])
    config = DeployConfig.from_dict(
        {
            **spec,
            "tpu": {
                "accelerator_type": "v5litepod-8",
                "zone": "us-central1-a",
                "project": "pygrid-tpu-demo",
            },
            "db": {"url": "grid.db"},
        }
    )
    return build_provider(config).render()


def main() -> None:
    for name in STACKS:
        out = HERE / name
        out.mkdir(parents=True, exist_ok=True)
        for fname, contents in render_stack(name).items():
            (out / fname).write_text(contents)
            print(f"wrote deploy/{name}/{fname}")


if __name__ == "__main__":
    main()
