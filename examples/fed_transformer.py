"""Federated transformer training — the flagship composition.

No reference analog (the reference's model zoo stops at the MNIST
MLP/CNN, SURVEY.md §5.7): K simulated clients each run local SGD on a
decoder-only transformer — the Pallas flash-attention kernel inside
every client step, bf16 mixed precision on TPU — and FedAvg aggregates
the diffs, all in ONE compiled program per round
(``parallel.make_fused_rounds`` over ``models.transformer`` — the
round-5 fused-aggregation builder whose final-step weight grads fold
into one matmul per layer, plus the bf16 CE backward on TPU). The
same composition trains over a client-sharded device mesh in
``__graft_entry__.dryrun_multichip`` (scenarios 8 and 9) and is
benchmarked on the real chip by ``bench.py bench_fed_transformer``.

The task is tiny on purpose (copy-class sequences): the point is the
composition converging, not the corpus.
"""

from __future__ import annotations

import os
import sys
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

if os.environ.get("PYGRID_TPU_FORCE_CPU"):
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import jax
import jax.numpy as jnp

from pygrid_tpu.models import transformer
from pygrid_tpu.parallel import make_fused_rounds
from pygrid_tpu.parallel.pallas_attention import flash_attention

K, B, L = 4, 4, 32          # clients × per-client batch × sequence length
ROUNDS = 30


def main() -> int:
    on_cpu = jax.devices()[0].platform == "cpu"
    cfg = transformer.TransformerConfig(
        vocab=32, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_len=L
    )
    loss_fn = partial(
        transformer.loss_and_acc,
        cfg=cfg,
        # the flash kernel Mosaic-compiles on TPU; interpret mode runs the
        # same kernel on CPU
        attn_fn=partial(flash_attention, interpret=on_cpu),
        # mixed precision (and the bf16 CE backward) earn their keep on
        # the MXU; on CPU they just slow the interpreter down
        compute_dtype=None if on_cpu else "bfloat16",
        ce_grad_dtype=None if on_cpu else "bfloat16",
    )

    # task: one base corpus, each client holding ITS OWN token shift of
    # it — non-iid shards whose next-token rule is learnable only jointly
    rng = np.random.default_rng(0)
    corpus = rng.integers(0, cfg.vocab, (B, L + 1))
    base = (corpus[None] + np.arange(K)[:, None, None]) % cfg.vocab
    X = jnp.asarray(base[..., :-1])
    y = jnp.asarray(base[..., 1:])

    params = transformer.init(jax.random.PRNGKey(0), cfg)
    rounds = make_fused_rounds(loss_fn, n_rounds=ROUNDS)
    final, losses, accs = rounds(params, X, y, jnp.float32(0.3))
    first, last = float(losses[0]), float(losses[-1])
    print(
        f"federated transformer: {K} clients × {ROUNDS} rounds "
        f"(flash attention, {'cpu interpret' if on_cpu else 'bf16 on TPU'}) — "
        f"loss {first:.3f} → {last:.3f}, acc {float(accs[-1]):.2f}"
    )
    if not last < first - 0.3:
        print("loss did not improve", file=sys.stderr)
        return 1

    # serve what you trained: KV-cache greedy decoding from the
    # federated params (models/decode.py; over a grid this same call
    # runs server-side via client.run_remote_generation)
    from pygrid_tpu.models import decode

    prompt = X[0, :1, :8]  # first 8 tokens of client 0's shard
    toks = decode.generate(final, prompt, 12, cfg)
    print(
        f"generated continuation of {list(map(int, prompt[0]))}: "
        f"{list(map(int, toks[0]))}"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
