"""End-to-end encrypted inference across the grid (SURVEY §3.5).

The reference's flagship privacy flow: a model owner shares an MLP's weights
over alice/bob/charlie (dan deals Beaver triples), serves the inference Plan
with ``mpc=True``; a data scientist discovers the model through the Network
(``/search-encrypted-model``, reference network.py:157-198), connects to the
share-holders, runs the Plan with every matmul a cross-node Beaver round,
and reconstructs the prediction client-side. No single node — provider
included — ever holds the weights, the input, or the output in the clear.

Run against the compose grid, or self-contained::

    python examples/encrypted_inference.py --spawn
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[0]))

import numpy as np

from _grid import example_args, spawn_grid, wait_for


def forward(x, w1, b1, w2, b2):
    """CryptoNets-style MLP: affine → square → affine. The square keeps the
    circuit polynomial — data-dependent nonlinearities (relu/max) need
    comparison protocols the ring doesn't give for free."""
    h = x @ w1 + b1
    h = h * h
    return h @ w2 + b2


def main() -> int:
    args = example_args(__doc__, need_network=True).parse_args()
    if args.spawn:
        network_url, nodes = spawn_grid(4)
    else:
        network_url = args.network
        nodes = {
            name: f"http://localhost:{port}"
            for name, port in zip(
                ["alice", "bob", "charlie", "dan"], [3000, 3001, 3002, 3003]
            )
        }
        wait_for(network_url, args.wait)

    from pygrid_tpu.client import DataCentricFLClient
    from pygrid_tpu.plans.plan import Plan
    from pygrid_tpu.smpc import EncryptedModel, publish_encrypted_model

    # ── model owner: build, share, serve ─────────────────────────────────
    rng = np.random.default_rng(0)
    weights = [
        rng.uniform(-0.5, 0.5, (4, 3)).astype(np.float32),
        rng.uniform(-0.2, 0.2, (3,)).astype(np.float32),
        rng.uniform(-0.5, 0.5, (3, 2)).astype(np.float32),
        rng.uniform(-0.2, 0.2, (2,)).astype(np.float32),
    ]
    plan = Plan(name="encrypted_forward", fn=forward)
    plan.build(np.zeros((2, 4), np.float32), *weights)

    clients = {n: DataCentricFLClient(url) for n, url in nodes.items()}
    publish_encrypted_model(
        plan,
        "encrypted-mlp",
        host_client=clients["alice"],
        holder_clients=[clients["alice"], clients["bob"], clients["charlie"]],
        provider_client=clients["dan"],
        weights=weights,
    )
    print("published encrypted-mlp: shares on alice/bob/charlie, dan deals")

    # ── data scientist: discover through the network, predict ───────────
    model = EncryptedModel.discover(network_url, "encrypted-mlp")
    x = rng.uniform(-1, 1, (2, 4)).astype(np.float32)
    pred = model.predict(x)
    expected = forward(x, *weights)
    err = float(np.max(np.abs(pred - expected)))
    print(f"encrypted prediction:\n{pred}")
    print(f"plaintext forward:\n{expected}")
    print(f"max abs error: {err:.4f} (fixed-point scale 1e-3, Beaver rounds)")
    assert err < 5e-2, "encrypted inference diverged from plaintext"
    print("encrypted inference OK — every matmul was a cross-node Beaver round")

    model.close()
    for c in clients.values():
        c.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
