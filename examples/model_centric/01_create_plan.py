"""Host a model-centric FL process: trace plans, define configs, host.

Mirror of reference ``examples/model-centric/01-Create-plan.ipynb``: build
the MNIST MLP (cell 10), trace the training plan (cells 16-24, there via
``PySyft func2plan(trace_autograd=True)``, here via ``jax.make_jaxpr``
inside ``Plan.build``), define client/server configs (cell 33), and host
everything on a node (cell 39)."""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np

from _grid import example_args, spawn_grid, wait_for

NAME, VERSION = "mnist", "1.0"
D, H, C, B = 784, 392, 10, 64


def main() -> int:
    args = example_args("host an FL process").parse_args()
    node_url = args.node
    if args.spawn:
        _, nodes = spawn_grid(1)
        node_url = nodes["alice"]
    wait_for(node_url, args.wait)

    import jax

    from pygrid_tpu.client import ModelCentricFLClient
    from pygrid_tpu.models import mlp
    from pygrid_tpu.plans.plan import Plan

    params = mlp.init(jax.random.PRNGKey(42), (D, H, C))
    training_plan = Plan(name="training_plan", fn=mlp.training_step)
    training_plan.build(
        np.zeros((B, D), np.float32),
        np.zeros((B, C), np.float32),
        np.float32(0.005),
        *[np.asarray(p) for p in params],
    )

    client = ModelCentricFLClient(node_url)
    response = client.host_federated_training(
        model=[np.asarray(p) for p in params],
        client_plans={"training_plan": training_plan},
        client_config={
            "name": NAME,
            "version": VERSION,
            "batch_size": B,
            "lr": 0.005,
            "max_updates": 100,
        },
        server_config={
            "min_workers": 2,
            "max_workers": 4,
            "pool_selection": "random",
            "do_not_reuse_workers_until_cycle": 6,
            "cycle_length": 28800,
            "num_cycles": 5,
            "max_diffs": 2,
            "min_diffs": 2,
            "minimum_upload_speed": 0,
            "minimum_download_speed": 0,
            "iterative_plan": True,
        },
    )
    client.close()
    print(f"hosted {NAME}/{VERSION} on {node_url}: {response}")
    return 0 if response.get("status") == "success" else 1


if __name__ == "__main__":
    sys.exit(main())
