"""Run FL workers against a hosted process until the cycles complete.

Mirror of reference ``examples/model-centric/02-ExecutePlan.ipynb`` (cells
7-15): N workers authenticate, request a cycle, download model + plan, run
local SGD via the plan, and report diffs; the node FedAvg-aggregates and
advances cycles. Checkpoint retrieval at the end mirrors
``/model-centric/retrieve-model`` (reference routes.py:471-516)."""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from _grid import example_args, wait_for

NAME, VERSION = "mnist", "1.0"


def main() -> int:
    parser = example_args("execute FL training cycles")
    parser.add_argument("--workers", type=int, default=2)
    parser.add_argument("--cycles", type=int, default=2)
    args = parser.parse_args()
    wait_for(args.node, args.wait)

    from pygrid_tpu.client import ModelCentricFLClient
    from pygrid_tpu.worker import run_worker

    total_accepted = 0
    for cycle in range(args.cycles):
        for w in range(args.workers):
            result = run_worker(args.node, NAME, VERSION, cycles=1)
            total_accepted += result.accepted
            print(
                f"cycle {cycle} worker {w}: accepted={result.accepted} "
                f"rejected={result.rejected} errors={result.errors}"
            )

    client = ModelCentricFLClient(args.node)
    try:
        checkpoint = client.retrieve_model(NAME, VERSION, "latest")
        print(f"latest checkpoint: {len(checkpoint)} tensors, "
              f"first shape {checkpoint[0].shape}")
    finally:
        client.close()
    return 0 if total_accepted else 1


if __name__ == "__main__":
    sys.exit(main())
