"""Advanced FL: FedAdam + differential privacy + top-k compression, together.

Everything beyond the reference's FedAvg in one hosted process:

- **FedAdam** (``server_config["server_optimizer"]``): the node treats the
  averaged diff as a pseudo-gradient and applies server-side Adam, state
  persisted across node restarts;
- **DP-FedAvg** (``server_config["differential_privacy"]``): every client
  diff clips to L2 ≤ C at ingest; the mean gets N(0, (z·C/K)²) noise;
- **top-k uploads** (``client_config["diff_compression"]``): workers ship
  the top 10% of entries per tensor with error feedback, over the binary
  bf16 wire.

Run self-contained::

    python examples/advanced_fl.py --spawn
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[0]))

import numpy as np

from _grid import example_args, spawn_grid, wait_for

K, D, H, C, B = 4, 64, 32, 10, 32
ROUNDS = 8


def main() -> int:
    args = example_args(__doc__).parse_args()
    if args.spawn:
        _, nodes = spawn_grid(1)
        node_url = nodes["alice"]
    else:
        node_url = args.node
        wait_for(node_url, args.wait)

    import jax

    from pygrid_tpu.client import FLClient, ModelCentricFLClient
    from pygrid_tpu.models import mlp
    from pygrid_tpu.plans.plan import Plan
    from pygrid_tpu.plans.state import serialize_model_params

    rng = np.random.default_rng(0)
    true_w = rng.normal(size=(D, C)).astype(np.float32)
    data_X = rng.normal(size=(K, B, D)).astype(np.float32)
    data_y = np.eye(C, dtype=np.float32)[
        np.argmax(data_X.reshape(-1, D) @ true_w, axis=1)
    ].reshape(K, B, C)

    params = [np.asarray(p) for p in mlp.init(jax.random.PRNGKey(0), (D, H, C))]
    plan = Plan(name="training_plan", fn=mlp.training_step)
    plan.build(
        np.zeros((B, D), np.float32),
        np.zeros((B, C), np.float32),
        np.float32(0.5),
        *params,
    )

    mc = ModelCentricFLClient(node_url)
    resp = mc.host_federated_training(
        model=params,
        client_plans={"training_plan": plan},
        client_config={
            "name": "advanced", "version": "1.0",
            "batch_size": B, "lr": 0.5, "max_updates": 1,
            "diff_precision": "bf16",
            "diff_compression": {"name": "topk", "fraction": 0.1},
        },
        server_config={
            "min_workers": K, "max_workers": K,
            "min_diffs": K, "max_diffs": K, "num_cycles": ROUNDS,
            "pool_selection": "random",
            "do_not_reuse_workers_until_cycle": 0,
            "server_optimizer": {
                "name": "adam", "lr": 0.3, "beta1": 0.9, "beta2": 0.99,
            },
            "differential_privacy": {
                "clip_norm": 5.0, "noise_multiplier": 0.01,
            },
        },
    )
    assert resp.get("status") == "success", resp

    import time

    clients = []
    for k in range(K):
        client = FLClient(node_url, wire="binary")
        auth = client.authenticate("advanced", "1.0")
        clients.append((client, auth["worker_id"], k))

    def request_until_accepted(client, wid):
        # the next cycle spawns when background aggregation finishes —
        # a rejected request means "retry shortly" (the reference's
        # reject+timeout contract)
        for _ in range(100):
            cyc = client.cycle_request(wid, "advanced", "1.0", 1.0, 100.0, 100.0)
            if cyc.get("status") == "accepted":
                return cyc
            time.sleep(0.1)
        raise RuntimeError(f"never accepted: {cyc}")

    plans = {}
    losses = []
    for _ in range(ROUNDS):
        accepted = []
        for client, wid, k in clients:
            cyc = request_until_accepted(client, wid)
            accepted.append((client, wid, k, cyc))
        round_losses = []
        for client, wid, k, cyc in accepted:
            model_params = client.get_model(
                wid, cyc["request_key"], cyc["model_id"], precision="bf16"
            )
            if k not in plans:
                plans[k] = client.get_plan(
                    wid, cyc["request_key"], cyc["plans"]["training_plan"]
                )
            out = plans[k](data_X[k], data_y[k], np.float32(0.5), *model_params)
            round_losses.append(float(out[0]))
            new_params = [np.asarray(t) for t in out[2:]]
            diff = [p - n for p, n in zip(model_params, new_params)]
            job = client.new_job("advanced", "1.0")
            job.worker_id, job.request_key = wid, cyc["request_key"]
            job.client_config = cyc.get("client_config") or {}
            job.report(diff)  # topk+bf16 per the hosted client_config
        losses.append(np.mean(round_losses))
    for client, _, _ in clients:
        client.close()

    print("losses per round:", [round(float(l), 3) for l in losses])
    assert losses[-1] < losses[0], "FedAdam+DP+topk did not learn"
    print(
        "advanced FL OK — server Adam on clipped/noised means of top-k "
        "bf16 diffs, and the loss still goes down"
    )
    mc.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
