"""Populate a node with private tagged tensors.

Mirror of reference
``examples/data-centric/mnist/01-FL-mnist-populate-a-grid-node.ipynb``:
login to a node, ``send`` dataset shards with #tags and descriptions so
data scientists can discover them via grid search."""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np

from _grid import example_args, spawn_grid, wait_for


def main() -> int:
    parser = example_args("populate a node with tagged data")
    parser.add_argument("--shards", type=int, default=4)
    args = parser.parse_args()
    node_url = args.node
    if args.spawn:
        _, nodes = spawn_grid(1)
        node_url = nodes["alice"]
    wait_for(node_url, args.wait)

    from pygrid_tpu.client import DataCentricFLClient

    client = DataCentricFLClient(node_url)
    client.login("admin", "admin")

    rng = np.random.default_rng(0)
    for shard in range(args.shards):
        X = rng.normal(size=(64, 784)).astype("float32")
        y = rng.integers(0, 10, size=(64,)).astype("int32")
        client.send(
            X,
            tags={"#X", "#mnist", f"#shard-{shard}"},
            description=f"MNIST images shard {shard}",
        )
        client.send(
            y,
            tags={"#Y", "#mnist", f"#shard-{shard}"},
            description=f"MNIST labels shard {shard}",
        )
    found = client.search("#mnist")
    print(f"sent {2 * args.shards} tensors to {node_url}; "
          f"search('#mnist') → {len(found)} pointers")
    client.close()
    return 0 if len(found) == 2 * args.shards else 1


if __name__ == "__main__":
    sys.exit(main())
