"""Train a model on remote data via pointer ops.

Mirror of reference
``examples/data-centric/mnist/02-FL-mnist-train-model.ipynb`` (cells
7-22): ``PublicGridNetwork.search`` discovers tagged shards across the
grid, then a linear model is trained where the data lives — every forward/
backward op is a remote pointer op executed in the node's party runtime,
only scalars (losses) and the final weights come back."""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np

from _grid import example_args, spawn_grid, wait_for


def main() -> int:
    parser = example_args("train on remote data via pointers")
    parser.add_argument("--epochs", type=int, default=20)
    parser.add_argument("--lr", type=float, default=0.1)
    args = parser.parse_args()
    network_url, node_url = args.network, args.node
    if args.spawn:
        network_url, nodes = spawn_grid(1)
        node_url = nodes["alice"]
    wait_for(node_url, args.wait)
    wait_for(network_url, args.wait)

    from pygrid_tpu.client import DataCentricFLClient, PublicGridNetwork

    owner = DataCentricFLClient(node_url)
    owner.login("admin", "admin")
    rng = np.random.default_rng(1)
    true_w = rng.normal(size=(4, 1)).astype("float32")
    X = rng.normal(size=(256, 4)).astype("float32")
    y = X @ true_w
    owner.send(X, tags={"#train-X", "#regression"})
    owner.send(y, tags={"#train-Y", "#regression"})

    network = PublicGridNetwork(network_url)
    X_ptrs = network.search("#train-X")
    y_ptrs = network.search("#train-Y")
    print(f"found shards on nodes: {sorted(X_ptrs)}")

    w = np.zeros((4, 1), dtype="float32")
    for epoch in range(args.epochs):
        losses = []
        for node_name in X_ptrs:
            X_ptr, y_ptr = X_ptrs[node_name][0], y_ptrs[node_name][0]
            w_ptr = X_ptr.location.send(w)
            pred = X_ptr @ w_ptr
            err = pred - y_ptr
            loss = (err * err).mean()
            # d/dw mse = 2/N · Xᵀ err, computed where the data lives
            grad_ptr = X_ptr.t() @ err
            grad = grad_ptr.get() * (2.0 / 256)
            w = w - args.lr * grad
            losses.append(float(np.asarray(loss.get())))
        print(f"epoch {epoch}: mse={np.mean(losses):.5f}")

    final_err = float(np.abs(w - true_w).max())
    print(f"max |w - w*| = {final_err:.4f}")
    network.close()
    owner.close()
    return 0 if final_err < 0.5 else 1


if __name__ == "__main__":
    sys.exit(main())
