"""Secure aggregation (Bonawitz double-masking) on the FL report path.

Four workers train one cycle where the node NEVER sees an individual
diff — only uint32-masked envelopes whose pairwise Threefry/Philox masks
cancel in the accumulator. One worker completes the key rounds and then
vanishes; the survivors' Shamir shares reconstruct exactly the dangling
mask terms, and the final checkpoint equals plain FedAvg of the
survivors' diffs to quantization precision (asserted).

Rounds per worker (client/secagg.py ``SecAggSession``):

1. ``advertise`` a Diffie–Hellman public key; poll the ``roster``;
2. Shamir-share the self-mask seed + DH secret, sealed per-peer,
   uploaded through the (untrusted) node;
3. report the quantized diff masked with PRG(self) ± PRG(pairwise);
4. answer the ``unmask`` round for the survivor/dropout sets.

Run self-contained::

    python examples/secagg_fl.py --spawn
"""

from __future__ import annotations

import sys
import threading
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[0]))

import numpy as np

from _grid import example_args, spawn_grid, wait_for

K, D, H, C, B = 4, 32, 16, 4, 16
CLIP = 0.5
NAME, VERSION = "secagg-demo", "1.0"


def main() -> int:
    args = example_args(__doc__).parse_args()
    if args.spawn:
        _, nodes = spawn_grid(1)
        node_url = nodes["alice"]
    else:
        node_url = args.node
        wait_for(node_url, args.wait)

    import jax

    from pygrid_tpu.client import FLClient, ModelCentricFLClient, SecAggSession
    from pygrid_tpu.federated import secagg
    from pygrid_tpu.models import mlp
    from pygrid_tpu.plans.plan import Plan

    params = [np.asarray(p) for p in mlp.init(jax.random.PRNGKey(0), (D, H, C))]
    plan = Plan(name="training_plan", fn=mlp.training_step)
    plan.build(
        np.zeros((B, D), np.float32),
        np.zeros((B, C), np.float32),
        np.float32(0.1),
        *params,
    )

    mc = ModelCentricFLClient(node_url)
    resp = mc.host_federated_training(
        model=params,
        client_plans={"training_plan": plan},
        client_config={
            "name": NAME, "version": VERSION,
            "batch_size": B, "lr": 0.1, "max_updates": 1,
        },
        server_config={
            "min_workers": K, "max_workers": K,
            # readiness at K-1 diffs: the demo's dropout must not stall it
            "min_diffs": K - 1, "max_diffs": K - 1, "num_cycles": 1,
            "pool_selection": "random",
            "do_not_reuse_workers_until_cycle": 0,
            "secure_aggregation": {
                "clip_range": CLIP,
                "threshold": K - 1,
                "phase_timeout": 15.0,
            },
        },
    )
    assert resp.get("status") == "success", resp

    rng = np.random.default_rng(7)
    diffs = {
        i: [rng.normal(0, 0.01, p.shape).astype(np.float32) for p in params]
        for i in range(K)
    }
    results: dict[int, str] = {}

    def worker(i: int, drop: bool) -> None:
        client = FLClient(node_url)
        auth = client.authenticate(NAME, VERSION)
        wid = auth["worker_id"]
        cyc = client.cycle_request(wid, NAME, VERSION, 1.0, 100.0, 100.0)
        assert cyc.get("status") == "accepted", cyc
        session = SecAggSession(client, wid, cyc["request_key"])
        session.advertise()
        session.wait_roster()
        session.upload_shares()
        session.wait_masking()
        if drop:
            results[i] = "dropped"
            print(f"worker {i}: completed key rounds, dropping before report")
            client.close()
            return
        session.report(diffs[i])
        results[i] = session.finish()
        print(f"worker {i}: reported masked diff, phase={results[i]}")
        client.close()

    threads = [
        threading.Thread(target=worker, args=(i, i == K - 1), daemon=True)
        for i in range(K)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    assert len(results) == K, f"stuck workers: {sorted(results)}"

    latest = mc.retrieve_model(NAME, VERSION)
    survivors = [i for i in range(K) if results[i] != "dropped"]
    expected = [
        p - np.mean([diffs[i][k] for i in survivors], axis=0)
        for k, p in enumerate(params)
    ]
    step = 1.0 / secagg.choose_scale(CLIP, K)
    worst = 0.0
    for got, want in zip(latest, expected):
        worst = max(worst, float(np.abs(np.asarray(got) - want).max()))
        np.testing.assert_allclose(
            np.asarray(got), want, atol=K * step + 1e-6
        )
    mc.close()
    print(
        f"secure aggregation OK: {len(survivors)}/{K} survivors averaged, "
        f"dropout unmasked via Shamir; checkpoint matches plain FedAvg "
        f"(max |Δ| = {worst:.2e}, quantization step {step:.2e})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
