"""Asynchronous federated learning (FedBuff) on the cycle protocol.

Three workers train against whatever checkpoint they downloaded and
report whenever they finish; the node folds each report into a
staleness-weighted buffer and flushes every ``buffer_size`` reports.
One worker is deliberately slow: its report arrives after a flush has
already advanced the model, re-homes to the current buffer, and is
discounted by (1+staleness)^-0.5 — the final checkpoint is asserted
against the hand-computed weighted math.

Run self-contained::

    python examples/async_fl.py --spawn
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[0]))

import numpy as np

from _grid import example_args, spawn_grid, wait_for

D, H, C, B = 16, 8, 4, 8
NAME, VERSION = "fedbuff-demo", "1.0"


def main() -> int:
    args = example_args(__doc__).parse_args()
    if args.spawn:
        _, nodes = spawn_grid(1)
        node_url = nodes["alice"]
    else:
        node_url = args.node
        wait_for(node_url, args.wait)

    import jax

    from pygrid_tpu.client import FLClient, ModelCentricFLClient
    from pygrid_tpu.federated.cycle_manager import staleness_weight
    from pygrid_tpu.models import mlp
    from pygrid_tpu.plans.plan import Plan
    from pygrid_tpu.plans.state import serialize_model_params

    params = [np.asarray(p) for p in mlp.init(jax.random.PRNGKey(0), (D, H, C))]
    plan = Plan(name="training_plan", fn=mlp.training_step)
    plan.build(
        np.zeros((B, D), np.float32),
        np.zeros((B, C), np.float32),
        np.float32(0.1),
        *params,
    )
    mc = ModelCentricFLClient(node_url)
    resp = mc.host_federated_training(
        model=params,
        client_plans={"training_plan": plan},
        client_config={
            "name": NAME, "version": VERSION,
            "batch_size": B, "lr": 0.1, "max_updates": 1,
        },
        server_config={
            "min_workers": 1, "max_workers": 8, "num_cycles": 2,
            "do_not_reuse_workers_until_cycle": 0,
            "pool_selection": "random",
            "async_aggregation": {
                "buffer_size": 2, "staleness_power": 0.5,
            },
        },
    )
    assert resp.get("status") == "success", resp

    def join():
        client = FLClient(node_url, timeout=30.0)
        wid = client.authenticate(NAME, VERSION)["worker_id"]
        cyc = client.cycle_request(wid, NAME, VERSION, 1.0, 100.0, 100.0)
        assert cyc.get("status") == "accepted", cyc
        return client, wid, cyc

    def diff(seed):
        rng = np.random.default_rng(seed)
        return [rng.normal(0, 0.01, p.shape).astype(np.float32) for p in params]

    def wait_new_ckpt(old_first):
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            got = mc.retrieve_model(NAME, VERSION)
            if not np.allclose(np.asarray(got[0]), old_first):
                return got
            time.sleep(0.05)
        raise TimeoutError("flush never landed")

    # slow worker downloads checkpoint 1 and goes quiet
    slow, slow_wid, slow_cyc = join()
    d_slow = diff(1)

    # two fast workers fill buffer #1 -> checkpoint 2
    fast = [join() for _ in range(2)]
    d_fast = [diff(2), diff(3)]
    for (client, wid, cyc), d in zip(fast, d_fast):
        client.report(wid, cyc["request_key"], serialize_model_params(d))
    ckpt2 = wait_new_ckpt(params[0])
    expect2 = [
        p - (a + b) / 2 for p, a, b in zip(params, d_fast[0], d_fast[1])
    ]
    for got, want in zip(ckpt2, expect2):
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-6)
    print("flush #1: 2 fresh reports averaged (weights 1, 1)")

    # the slow worker finally reports — stale by one checkpoint — and a
    # fresh worker completes buffer #2
    slow.report(slow_wid, slow_cyc["request_key"], serialize_model_params(d_slow))
    fresh, fresh_wid, fresh_cyc = join()
    d_fresh = diff(4)
    fresh.report(fresh_wid, fresh_cyc["request_key"], serialize_model_params(d_fresh))
    w = staleness_weight(1, 0.5)
    expect3 = [
        p2 - (w * a + b) / (w + 1)
        for p2, a, b in zip(expect2, d_slow, d_fresh)
    ]
    ckpt3 = wait_new_ckpt(np.asarray(ckpt2[0]))
    worst = 0.0
    for got, want in zip(ckpt3, expect3):
        worst = max(worst, float(np.abs(np.asarray(got) - want).max()))
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-6)
    print(
        f"flush #2: stale report discounted to weight {w:.3f}, fresh at 1 "
        f"(max |Δ| vs hand math = {worst:.2e})"
    )
    for client, *_ in (fast + [(slow,), (fresh,)]):
        client.close()
    mc.close()
    print("async FL OK: FedBuff staleness-weighted buffered aggregation")
    return 0


if __name__ == "__main__":
    sys.exit(main())
