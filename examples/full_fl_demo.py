"""End-to-end model-centric FL demo: host + train + checkpoint.

Combines 01_create_plan and 02_execute_plan into one driver (what the
compose ``worker`` service runs): host the MNIST process on a node, run N
workers per cycle until the configured cycles finish, then pull the final
checkpoint. Equivalent to running the reference's two model-centric
notebooks back-to-back against the compose grid."""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[0]))

from _grid import example_args, spawn_grid, wait_for

HERE = Path(__file__).resolve().parent


def main() -> int:
    parser = example_args("full FL round-trip demo")
    parser.add_argument("--workers", type=int, default=2)
    parser.add_argument("--cycles", type=int, default=2)
    args = parser.parse_args()
    node_url = args.node
    if args.spawn:
        _, nodes = spawn_grid(1)
        node_url = nodes["alice"]
    wait_for(node_url, args.wait)

    base = [sys.executable, "-u"]
    host = subprocess.run(
        [*base, str(HERE / "model_centric" / "01_create_plan.py"),
         "--node", node_url],
        timeout=600,
    )
    if host.returncode:
        return host.returncode
    execute = subprocess.run(
        [*base, str(HERE / "model_centric" / "02_execute_plan.py"),
         "--node", node_url, "--workers", str(args.workers),
         "--cycles", str(args.cycles)],
        timeout=600,
    )
    return execute.returncode


if __name__ == "__main__":
    sys.exit(main())
