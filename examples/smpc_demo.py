"""SMPC: fixed-precision additive sharing + Beaver matmul.

Mirror of the reference's SMPC surface (intro notebooks;
``tests/data_centric/test_basic_syft_operations.py:383-457``): encode
floats into the 2^64 ring, split into additive shares held by parties
alice/bob/charlie with crypto-provider james, run add/sub/mul/matmul on
shares, reconstruct. TPU-native: every share op is a jitted/vmapped XLA
kernel over uint64 limbs — batches of parties are one array axis."""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[0]))

import numpy as np

from pygrid_tpu.smpc import CryptoProvider
from pygrid_tpu.smpc.additive import fix_prec

PARTIES = ("alice", "bob", "charlie")


def main() -> int:
    provider = CryptoProvider(id="james")
    x = np.array([[0.1, 0.2], [0.3, 0.4]], dtype="float64")
    y = np.array([[2.0, 0.5], [1.0, -1.0]], dtype="float64")

    sx = fix_prec(x).share(*PARTIES, crypto_provider=provider)
    sy = fix_prec(y).share(*PARTIES, crypto_provider=provider)
    print(f"x shared over {len(PARTIES)} parties; one share of x[0,0]: "
          f"{np.asarray(sx.shares)[0].ravel()[0]} (mod 2^64 — reveals nothing)")

    results = {
        "x + y": (sx + sy).get(),
        "x - y": (sx - sy).get(),
        "x * y (Beaver)": (sx * sy).get(),
        "x @ y (Beaver)": (sx @ sy).get(),
    }
    expect = {
        "x + y": x + y,
        "x - y": x - y,
        "x * y (Beaver)": x * y,
        "x @ y (Beaver)": x @ y,
    }
    ok = True
    for op, result in results.items():
        err = float(np.abs(np.asarray(result) - expect[op]).max())
        print(f"{op:>16}: max err {err:.2e}")
        ok &= err < 1e-2
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
