"""Shared example plumbing: arg parsing, wait-for-node, optional ephemeral
in-process grid (the reference examples assume the compose grid is up;
``--spawn`` removes that requirement)."""

from __future__ import annotations

import argparse
import asyncio
import os
import socket
import threading
import time

import requests

if os.environ.get("PYGRID_TPU_FORCE_CPU"):
    # the session sitecustomize pins jax to the real TPU platform; tests run
    # the examples on the virtual CPU mesh instead (tests/conftest.py)
    import jax

    jax.config.update("jax_platforms", "cpu")


def wait_for(url: str, timeout: float = 60.0) -> None:
    """Poll until the server answers (compose services race their deps)."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            requests.get(url + "/", timeout=2)
            return
        except requests.ConnectionError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _Server:
    def __init__(self, app, port: int) -> None:
        self.port = port
        self.url = f"http://127.0.0.1:{port}"
        self._loop = asyncio.new_event_loop()
        self._ready = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(app,), daemon=True
        )
        self._thread.start()
        self._ready.wait(15)

    def _run(self, app) -> None:
        from aiohttp import web

        asyncio.set_event_loop(self._loop)

        async def go():
            runner = web.AppRunner(app)
            await runner.setup()
            await web.TCPSite(runner, "127.0.0.1", self.port).start()
            self._ready.set()

        self._loop.run_until_complete(go())
        self._loop.run_forever()


def spawn_grid(n_nodes: int = 4):
    """Ephemeral in-process grid; returns (network_url, {name: node_url})."""
    from pygrid_tpu.network import create_app as network_app
    from pygrid_tpu.node import create_app as node_app

    network = _Server(network_app("example-network"), _free_port())
    nodes = {}
    for name in ["alice", "bob", "charlie", "dan"][:n_nodes]:
        server = _Server(node_app(name), _free_port())
        requests.post(
            network.url + "/join",
            json={"node-id": name, "node-address": server.url},
            timeout=10,
        ).raise_for_status()
        nodes[name] = server.url
    return network.url, nodes


def example_args(description: str, need_network: bool = False):
    parser = argparse.ArgumentParser(description=description)
    parser.add_argument("--node", default="http://localhost:5000")
    parser.add_argument("--network", default="http://localhost:7000")
    parser.add_argument("--spawn", action="store_true",
                        help="spawn an ephemeral in-process grid")
    parser.add_argument("--wait", type=float, default=60.0,
                        help="seconds to wait for servers")
    return parser
